"""Restore controller: phase machine driving pod restoration.

Parity: reference ``pkg/gritmanager/controllers/restore/restore_controller.go``
— phases Created→Pending→Restoring→Restored/Failed (:60-65); waits for the
pod webhook's claim, schedules the restore-mode agent Job on the target pod's
node, declares success when the pod reaches Running.
"""

from __future__ import annotations

from collections.abc import Callable

from grit_tpu.obs.metrics import AGENT_JOB_RETRIES, PHASE_TRANSITIONS
from grit_tpu.api.constants import (
    CLONE_ORDINAL_ANNOTATION,
    FAULT_POINTS_ANNOTATION,
    GRIT_AGENT_LABEL,
    GRIT_AGENT_NAME,
    MIGRATION_PATH_ANNOTATION,
    RESTORE_NAME_ANNOTATION,
    RETRY_AT_ANNOTATION,
)
from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.manager import watchdog
from grit_tpu.api.types import Restore, RestorePhase
from grit_tpu.kube.cluster import AlreadyExists, Cluster
from grit_tpu.kube.controller import Request, Result
from grit_tpu.kube.objects import OwnerReference, Pod
from grit_tpu.manager.agentmanager import AgentJobParams, AgentManager
from grit_tpu.manager.util import (
    agent_job_name,
    cr_candidates_from_agent_job,
    migration_flight_clock,
    migration_traceparent,
    sync_progress_status,
    update_condition,
)
from grit_tpu.obs import flight, trace


def _clone_ordinal_of(restore: Restore) -> int:
    """The RestoreSet clone ordinal stamped on this Restore, or -1 for
    a plain restore (a malformed annotation reads as plain — the
    ordinal is an observability key, never correctness)."""
    raw = restore.metadata.annotations.get(CLONE_ORDINAL_ANNOTATION, "")
    try:
        k = int(raw)
    except ValueError:
        return -1
    return k if k >= 0 else -1


class RestoreController:
    kind = "Restore"

    def __init__(self, agent_manager: AgentManager) -> None:
        self.agent_manager = agent_manager
        self._handlers: dict[RestorePhase, Callable[[Cluster, Restore], Result]] = {
            RestorePhase.CREATED: self._created,
            RestorePhase.PENDING: self._pending,
            RestorePhase.RESTORING: self._restoring,
            RestorePhase.RESTORED: self._restored,
            RestorePhase.FAILED: self._failed,
        }

    # Watch pods carrying grit.dev/restore-name (reference Register :241-255)
    # and our agent Jobs — without the Job watch a failed restore agent Job
    # would go unnoticed while the target pod sits in Pending forever.
    def register(self, cluster: Cluster, enqueue: Callable[[Request], None]) -> None:
        def on_pod_event(ev) -> None:
            name = ev.obj.metadata.annotations.get(RESTORE_NAME_ANNOTATION)
            if name:
                enqueue(Request(ev.namespace, name))

        def on_job_event(ev) -> None:
            if ev.obj.metadata.labels.get(GRIT_AGENT_LABEL) != GRIT_AGENT_NAME:
                return
            # Raw name plus the slice-CR candidate for per-host gang
            # Jobs — see the checkpoint controller's register.
            for cr in cr_candidates_from_agent_job(ev.name):
                enqueue(Request(ev.namespace, cr))

        cluster.watch("Pod", on_pod_event)
        cluster.watch("Job", on_job_event)

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        faults.fault_point("manager.restore.reconcile")
        restore = cluster.try_get("Restore", req.name, req.namespace)
        if restore is None:
            return Result()
        phase = restore.status.phase or RestorePhase.CREATED
        parent = migration_traceparent(cluster, restore, "Restore")
        with trace.span(f"manager.restore.{phase.value}", parent=parent,
                        restore=f"{req.namespace}/{req.name}"):
            return self._handlers[phase](cluster, restore)

    def _set_phase(
        self, cluster: Cluster, restore: Restore, phase: RestorePhase,
        reason: str, message: str = "", **status_fields,
    ) -> None:
        def mutate(obj: Restore) -> None:
            obj.status.phase = phase
            for k, v in status_fields.items():
                setattr(obj.status, k, v)
            update_condition(obj.status.conditions, phase.value, "True", reason, message)

        cluster.patch("Restore", restore.metadata.name, mutate, restore.metadata.namespace)
        PHASE_TRANSITIONS.inc(kind="Restore", phase=phase.value)
        # Keyed to the CHECKPOINT name: the agents derive their uid from
        # the work/stage dir basename, which is the checkpoint name.
        flight.emit("manager.phase", uid=restore.spec.checkpoint_name,
                    kind="Restore", phase=phase.value, reason=reason)

    def _fail(self, cluster: Cluster, restore: Restore, reason: str, msg: str) -> Result:
        self._set_phase(cluster, restore, RestorePhase.FAILED, reason, msg)
        return Result()

    def _selected_pods(self, cluster: Cluster, restore: Restore) -> list[Pod]:
        return [
            p for p in cluster.list("Pod", restore.metadata.namespace)
            if p.metadata.annotations.get(RESTORE_NAME_ANNOTATION) == restore.metadata.name
        ]

    # createdHandler (reference :97-133): wait until the pod webhook annotated
    # a replacement pod with our name; exactly one pod must match.
    def _created(self, cluster: Cluster, restore: Restore) -> Result:
        pods = self._selected_pods(cluster, restore)
        if not pods:
            return Result()  # re-enqueued by the pod watch
        if len(pods) > 1:
            return self._fail(
                cluster, restore, "MultiplePodsSelected",
                f"{len(pods)} pods carry {RESTORE_NAME_ANNOTATION}={restore.metadata.name}",
            )
        self._set_phase(cluster, restore, RestorePhase.PENDING, "TargetPodSelected",
                        target_pod=pods[0].metadata.name)
        return Result(requeue=True)

    # pendingHandler (reference :137-190): wait for scheduling, then create the
    # restore-mode agent Job on the pod's node (download PVC → hostPath).
    def _pending(self, cluster: Cluster, restore: Restore) -> Result:
        # Backoff gate: a watchdog-scheduled retry may not create the
        # next agent Job before grit.dev/retry-at.
        wait = watchdog.retry_wait_remaining(restore.metadata)
        if wait > 0:
            return Result(requeue_after=wait)
        pod = cluster.try_get("Pod", restore.status.target_pod, restore.metadata.namespace)
        if pod is None:
            return self._fail(cluster, restore, "TargetPodDeleted",
                              f"target pod {restore.status.target_pod} deleted")
        if not pod.spec.node_name:
            return Result()  # not scheduled yet; pod watch re-enqueues
        ckpt = cluster.try_get(
            "Checkpoint", restore.spec.checkpoint_name, restore.metadata.namespace
        )
        pvc = (ckpt.spec.volume_claim.claim_name
               if ckpt is not None and ckpt.spec.volume_claim else None)
        job = self.agent_manager.generate_agent_job(AgentJobParams(
            cr_name=restore.spec.checkpoint_name,  # data path keyed by ckpt name
            namespace=restore.metadata.namespace,
            action="restore",
            node_name=pod.spec.node_name,
            pvc_claim_name=pvc,
            target_pod_name=pod.metadata.name,
            target_pod_uid=pod.metadata.uid,
            owner=OwnerReference(kind="Restore", name=restore.metadata.name,
                                 uid=restore.metadata.uid, controller=True),
            traceparent=restore.metadata.annotations.get(
                trace.TRACEPARENT_ANNOTATION, ""),
            # Same data path as the checkpoint half: from this Restore's
            # annotation (the auto-migration flow copies it over), falling
            # back to the Checkpoint CR's.
            migration_path=(
                restore.metadata.annotations.get(MIGRATION_PATH_ANNOTATION)
                or (ckpt.metadata.annotations.get(MIGRATION_PATH_ANNOTATION,
                                                  "")
                    if ckpt is not None else "")),
            fault_points=(
                restore.metadata.annotations.get(FAULT_POINTS_ANNOTATION)
                or (ckpt.metadata.annotations.get(FAULT_POINTS_ANNOTATION,
                                                  "")
                    if ckpt is not None else "")),
            flight_clock=migration_flight_clock(cluster, restore, "Restore"),
            # RestoreSet clone legs: the set controller stamps the
            # ordinal annotation on each clone Restore; riding it into
            # the agent env keys the leg's live progress snapshots
            # apart from its siblings (they all share the snapshot-name
            # uid — the watch --restoreset disambiguation).
            clone_ordinal=_clone_ordinal_of(restore),
        ))
        # Job is named after the *Restore* CR so checkpoint/restore jobs for
        # the same Checkpoint can't collide (reference names it after the CR
        # being reconciled, util.go:107-123).
        job.metadata.name = agent_job_name(restore.metadata.name)
        # ... and the heartbeat lease must renew the annotation on the
        # Job's FINAL name, not the checkpoint-keyed one it was rendered
        # under.
        for env_var in job.spec.template.spec.containers[0].env:
            if env_var.name == config.JOB_NAME.name:
                env_var.value = job.metadata.name
        try:
            cluster.create(job)
        except AlreadyExists:
            pass
        self._set_phase(cluster, restore, RestorePhase.RESTORING, "AgentJobCreated",
                        node_name=pod.spec.node_name)
        return Result()

    # restoringHandler (reference :193-212): success == target pod Running.
    def _restoring(self, cluster: Cluster, restore: Restore) -> Result:
        pod = cluster.try_get("Pod", restore.status.target_pod, restore.metadata.namespace)
        if pod is None:
            return self._fail(cluster, restore, "TargetPodDeleted",
                              f"target pod {restore.status.target_pod} deleted")
        if pod.status.phase == "Failed":
            return self._fail(cluster, restore, "TargetPodFailed",
                              f"target pod {restore.status.target_pod} failed")
        if pod.status.phase != "Running":
            staged = any(
                c.type == "DataStaged" and c.status == "True"
                for c in restore.status.conditions
            )
            job = cluster.try_get(
                "Job", agent_job_name(restore.metadata.name),
                restore.metadata.namespace,
            )
            if job is not None and job.status.complete() and not staged:
                # Terminal progress sync — see the checkpoint
                # controller: a finished leg's CR must not keep a
                # mid-flight snapshot forever.
                sync_progress_status(cluster, "Restore", restore, job)

                def mark(obj: Restore) -> None:
                    update_condition(obj.status.conditions, "DataStaged",
                                     "True", "AgentJobSucceeded")
                cluster.patch("Restore", restore.metadata.name, mark,
                              restore.metadata.namespace)
                return Result()
            if job is None and not staged:
                # The staging Job vanished before completing and the pod
                # never started — restore data will never land. (A Job that
                # completed and was then GC'd keeps its DataStaged record.)
                return self._fail(cluster, restore, "AgentJobLost",
                                  "restore agent job disappeared before pod start")
            if job is not None and job.status.is_failed():
                return self._leg_failure(cluster, restore,
                                         watchdog.AGENT_JOB_FAILED,
                                         "restore agent job failed")
            if job is not None and not staged:
                # Live telemetry on the same lease-cadence poll: frames
                # received / place waterline / ETA onto status.progress.
                sync_progress_status(cluster, "Restore", restore, job)
                cause = watchdog.overrun_cause(
                    job,
                    watchdog.phase_started_at(restore.status.conditions,
                                              RestorePhase.RESTORING.value),
                    kind="Restore")
                if cause is not None:
                    return self._leg_failure(
                        cluster, restore, cause,
                        f"restore agent job overran its "
                        f"{watchdog.overrun_noun(cause)}")
                return Result(requeue_after=watchdog.lease_timeout_s() / 2)
            return Result()
        self._set_phase(cluster, restore, RestorePhase.RESTORED, "PodRunning")
        return Result(requeue=True)

    def _leg_failure(self, cluster: Cluster, restore: Restore, cause: str,
                     message: str) -> Result:
        """Watchdog verdict for a failed/wedged restore agent Job: bounded
        backoff retry for retriable causes (delete Job, back through
        Pending once grit.dev/retry-at elapses — _failed drives that),
        fail fast with the agent's recorded reason otherwise. No abort arm
        here: the destination holds no quiesced workload, and the source
        side of a managed migration was already handled at SUBMITTING
        (harness/CLI concurrent flows resume the source through the
        checkpoint agent's own error path or an explicit run_abort)."""
        verdict = watchdog.classify_job_failure(
            self.agent_manager, restore.metadata.namespace,
            restore.spec.checkpoint_name, cause, message)
        attempt = watchdog.attempt_count(restore.metadata)
        if verdict.retriable and attempt < watchdog.max_attempts():
            if cause in watchdog.OVERRUN_CAUSES:
                # Wedged-but-Active Job: the retry replaces it now.
                cluster.try_delete(
                    "Job", agent_job_name(restore.metadata.name),
                    restore.metadata.namespace)
            delay = watchdog.schedule_retry(
                cluster, "Restore", restore.metadata.name,
                restore.metadata.namespace, attempt)
            AGENT_JOB_RETRIES.inc(kind="Restore", cause=verdict.cause)
            self._set_phase(
                cluster, restore, RestorePhase.FAILED, verdict.cause,
                f"{verdict.message} (attempt {attempt + 1}/"
                f"{watchdog.max_attempts()}, retry in {delay:.1f}s)")
            return Result(requeue_after=delay)
        return self._fail(cluster, restore, verdict.cause, verdict.message)

    # restoredHandler (reference :215-228): GC the agent Job.
    def _restored(self, cluster: Cluster, restore: Restore) -> Result:
        cluster.try_delete(
            "Job", agent_job_name(restore.metadata.name), restore.metadata.namespace
        )
        return Result()

    # Failed: unattended recovery for watchdog-sanctioned retries only. A
    # Restore that failed with grit.dev/retry-at stamped re-creates its
    # agent Job (through Pending) once the backoff elapses; everything
    # else — terminal classifications, webhook failures, pod-selection
    # dead-ends — stays Failed for the operator, as before.
    def _failed(self, cluster: Cluster, restore: Restore) -> Result:
        if RETRY_AT_ANNOTATION not in restore.metadata.annotations:
            return Result()
        wait = watchdog.retry_wait_remaining(restore.metadata)
        if wait > 0:
            return Result(requeue_after=wait)
        if not restore.status.target_pod:
            return Result()  # nothing to retry toward
        cluster.try_delete("Job", agent_job_name(restore.metadata.name),
                           restore.metadata.namespace)

        def strip(obj: Restore) -> None:
            obj.metadata.annotations.pop(RETRY_AT_ANNOTATION, None)

        cluster.patch("Restore", restore.metadata.name, strip,
                      restore.metadata.namespace)
        self._set_phase(cluster, restore, RestorePhase.PENDING,
                        "RetryAfterFailure")
        return Result(requeue=True)
