"""Controller utilities: pod identity hashing, condition helpers, phase
recovery, agent-Job naming.

Parity: reference ``pkg/gritmanager/controllers/util/util.go``.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any

from grit_tpu.api.constants import (
    COMPILE_CACHE_DEFAULT_DIR,
    COMPILE_CACHE_ENV,
)
from grit_tpu.api.types import CheckpointPhase, RestorePhase
from grit_tpu.kube.objects import Condition, PodSpec, now

# Agent job name mapping (reference util.go:107-123): Job "grit-agent-<cr>".
AGENT_JOB_PREFIX = "grit-agent-"

# Gang slice migration: one agent Job per host of the slice, named
# "grit-agent-<cr>-h<k>" (each with its OWN heartbeat lease — the
# per-host lease is just the PR 3 lease on the per-host Job).
_SLICE_MEMBER_RE = re.compile(r"^(?P<cr>.+)-h(?P<ord>\d{4})$")


def agent_job_name(cr_name: str) -> str:
    return AGENT_JOB_PREFIX + cr_name


def slice_member_name(cr_name: str, ordinal: int) -> str:
    """The per-host suffix a slice CR's agent Jobs carry."""
    return f"{cr_name}-h{ordinal:04d}"


def slice_agent_job_name(cr_name: str, ordinal: int) -> str:
    return agent_job_name(slice_member_name(cr_name, ordinal))


def parse_slice_member(name: str) -> tuple[str, int | None]:
    """``("<cr>", k)`` when ``name`` carries a per-host suffix, else
    ``(name, None)``."""
    m = _SLICE_MEMBER_RE.match(name)
    if m is None:
        return name, None
    return m.group("cr"), int(m.group("ord"))


def cr_name_from_agent_job(job_name: str) -> str | None:
    if job_name.startswith(AGENT_JOB_PREFIX):
        return job_name[len(AGENT_JOB_PREFIX):]
    return None


def cr_candidates_from_agent_job(job_name: str) -> list[str]:
    """CR names a Job event may belong to: the raw mapping, plus — for
    per-host slice Jobs (``grit-agent-<cr>-h<k>``) — the slice CR. Both
    are enqueued by the watch handlers: reconciling a name that is not
    a CR is a cheap no-op, and enqueuing both means a (legal) CR whose
    own name happens to end in ``-h0001`` still gets its events."""
    raw = cr_name_from_agent_job(job_name)
    if raw is None:
        return []
    base, ordinal = parse_slice_member(raw)
    return [raw] if ordinal is None else [raw, base]


# -- pod-spec hashing ------------------------------------------------------------

_FNV32_OFFSET = 2166136261
_FNV32_PRIME = 16777619


def fnv32a(data: bytes) -> int:
    """FNV-1a 32-bit — same hash family the reference uses for pod identity
    (util.go:133-163 uses hash/fnv New32a)."""

    h = _FNV32_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV32_PRIME) & 0xFFFFFFFF
    return h


def _normalize(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _normalize(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: _normalize(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_normalize(v) for v in obj]
    return obj


def compute_pod_spec_hash(spec: PodSpec) -> str:
    """Hash of a PodSpec with node-varying fields zeroed, so a replacement pod
    created by the same controller on a *different node* still matches its
    checkpoint. Zeroed fields follow reference util.go:133-163: nodeName, and
    the per-pod random ``kube-api-access-*`` projected volume name and its
    volumeMounts. We canonicalise to sorted JSON and FNV-32a it."""

    norm = _normalize(spec)  # _normalize builds fresh dicts; input is not mutated
    norm["node_name"] = ""
    for vol in norm.get("volumes", []):
        if str(vol.get("name", "")).startswith("kube-api-access-"):
            vol["name"] = ""
    for c in norm.get("containers", []):
        for vm in c.get("volume_mounts", []):
            if str(vm.get("name", "")).startswith("kube-api-access-"):
                vm["name"] = ""
        # The restore webhook injects COMPILE_CACHE_ENV=<default>; strip
        # exactly that pair so a previously-restored pod checkpointed
        # AGAIN still matches its next (not-yet-mutated) replacement —
        # migration chains. Operator-set values (any other value) stay in
        # the hash: they are template content, and stripping them would
        # also invalidate pod_spec_hashes stored before this change.
        c["env"] = [e for e in c.get("env", [])
                    if not (e.get("name") == COMPILE_CACHE_ENV and
                            e.get("value") == COMPILE_CACHE_DEFAULT_DIR)]
    payload = json.dumps(norm, sort_keys=True, separators=(",", ":")).encode()
    return format(fnv32a(payload), "x")


# -- condition helpers -----------------------------------------------------------


def update_condition(
    conditions: list[Condition], ctype: str, status: str, reason: str, message: str = ""
) -> list[Condition]:
    """Upsert a condition by type (reference util.go:173-202)."""

    for c in conditions:
        if c.type == ctype:
            if c.status != status or c.reason != reason or c.message != message:
                c.status = status
                c.reason = reason
                c.message = message
                c.last_transition_time = now()
            return conditions
    conditions.append(
        Condition(
            type=ctype, status=status, reason=reason, message=message,
            last_transition_time=now(),
        )
    )
    return conditions


def remove_condition(conditions: list[Condition], ctype: str) -> list[Condition]:
    """reference util.go:204-214."""

    return [c for c in conditions if c.type != ctype]


def resolve_last_checkpoint_phase(conditions: list[Condition]) -> CheckpointPhase:
    """Recover the last non-failed phase from the condition trail so a Failed
    machine can retry once the cause clears (reference util.go:218-234):
    walk conditions newest-first, return the first whose type names a phase
    other than Failed."""

    order = [
        CheckpointPhase.SUBMITTED,
        CheckpointPhase.SUBMITTING,
        CheckpointPhase.CHECKPOINTED,
        CheckpointPhase.FIRING,
        CheckpointPhase.STANDBY,
        CheckpointPhase.CHECKPOINTING,
        CheckpointPhase.PENDING,
        CheckpointPhase.CREATED,
    ]
    have = {c.type for c in conditions if c.status == "True"}
    for phase in order:
        if phase.value in have:
            return phase
    return CheckpointPhase.CREATED


def resolve_last_restore_phase(conditions: list[Condition]) -> RestorePhase:
    order = [
        RestorePhase.RESTORED,
        RestorePhase.RESTORING,
        RestorePhase.PENDING,
        RestorePhase.CREATED,
    ]
    have = {c.type for c in conditions if c.status == "True"}
    for phase in order:
        if phase.value in have:
            return phase
    return RestorePhase.CREATED


def migration_traceparent(cluster, obj, kind: str):
    """The CR's migration trace context, minted on first use.

    One migration is one trace: the context is stamped into the CR's
    ``grit.dev/traceparent`` annotation (the same annotation-propagation
    idiom as the rest of the control plane) so every reconcile, the agent
    Job (via TRACEPARENT env), and the shim (via the pod annotation
    passthrough) join the same trace. Returns None when tracing is off
    (grit_tpu/obs/trace.py is a noop then).
    """
    import secrets

    from grit_tpu.obs import trace

    if not trace.enabled():
        return None
    ann = obj.metadata.annotations.get(trace.TRACEPARENT_ANNOTATION, "")
    ctx = trace.parse_traceparent(ann) if ann else None
    if ctx is None:
        ctx = trace.SpanContext(trace_id=secrets.token_hex(16),
                                span_id=secrets.token_hex(8))
        tp = ctx.traceparent()

        def mutate(o):
            o.metadata.annotations[trace.TRACEPARENT_ANNOTATION] = tp

        cluster.patch(kind, obj.metadata.name, mutate, obj.metadata.namespace)
        obj.metadata.annotations[trace.TRACEPARENT_ANNOTATION] = tp
    return ctx


def migration_flight_clock(cluster, obj, kind: str) -> str:
    """The CR's flight-recorder clock anchor, minted on first use.

    When flight recording is on, the manager stamps its own wall/
    monotonic clock pair into ``grit.dev/flight-clock`` (same
    annotation-propagation idiom as the traceparent); the AgentManager
    forwards it into both agent Jobs' env so their flight logs carry a
    ``clock.manager`` event — the Job-annotation half of gritscope's
    cross-process clock alignment. Returns the JSON pair, or "" when
    flight recording is off.
    """
    import json as _json

    from grit_tpu.api.constants import FLIGHT_CLOCK_ANNOTATION
    from grit_tpu.obs import flight

    if not flight.enabled():
        return ""
    ann = obj.metadata.annotations.get(FLIGHT_CLOCK_ANNOTATION, "")
    if ann:
        return ann
    pair = _json.dumps(flight.clock_pair())

    def mutate(o):
        o.metadata.annotations[FLIGHT_CLOCK_ANNOTATION] = pair

    cluster.patch(kind, obj.metadata.name, mutate, obj.metadata.namespace)
    obj.metadata.annotations[FLIGHT_CLOCK_ANNOTATION] = pair
    return pair


def sync_progress_status(cluster, kind: str, obj, job) -> None:
    """Fold the agent Job's ``grit.dev/progress`` annotation into the
    CR's ``status.progress`` — the CRD half of the live telemetry plane.

    Called from the controllers' mid-phase poll (which already runs on
    the lease-renewal cadence), so the status subresource updates exactly
    as often as the agent's lease patch that carried the snapshot: no
    new write amplification anywhere on the path. A no-op when the Job
    carries no snapshot or nothing changed (the cluster's patch helper
    already skips identical writes, but skipping here avoids the
    read-modify-write round trip entirely).

    Single-host source legs additionally publish a ``nodePairs``
    ``src->dst`` bandwidth line aggregated from the snapshot's
    ``wire-k`` stream channels — the per-link accounting the fleet
    budgeter needs for EVERY member migration, not just slices (whose
    N×N twin is ``hostPairs``). The source node comes from the CR's
    status; the destination from the plan controller's
    grit.dev/destination-node stamp ("?" for unplanned migrations —
    the restore side lands wherever its owner reschedules)."""
    from grit_tpu.api.constants import (  # noqa: PLC0415 — avoid cycle
        DESTINATION_NODE_ANNOTATION,
    )
    from grit_tpu.manager import watchdog  # noqa: PLC0415 — avoid cycle
    from grit_tpu.obs import progress as progress_mod  # noqa: PLC0415

    snapshot = watchdog.job_progress(job)
    if snapshot is None:
        return
    snapshot = dict(snapshot)
    totals = progress_mod.wire_channel_totals(snapshot)
    src = getattr(obj.status, "node_name", "")
    if totals is not None and src:
        dst = obj.metadata.annotations.get(
            DESTINATION_NODE_ANNOTATION, "") or "?"
        snapshot["nodePairs"] = {f"{src}->{dst}": totals}
    if obj.status.progress == snapshot:
        return

    def mutate(o) -> None:
        o.status.progress = dict(snapshot)

    cluster.patch(kind, obj.metadata.name, mutate, obj.metadata.namespace)


def sync_slice_progress_status(cluster, kind: str, obj, jobs) -> None:
    """Slice fan-in twin of :func:`sync_progress_status`: fold EVERY
    per-host agent Job's progress annotation into one aggregate
    ``status.progress`` — per-host snapshots under ``hosts`` (keyed by
    ordinal), summed bytes/rate, the slowest host's ETA (the gang
    finishes when its last host does), and the per-host-pair bandwidth
    lines (``hostPairs``) the fleet scheduler's N×N budgeting consumes.

    ``jobs`` maps host ordinal → Job (None entries skipped). Same
    no-op-on-unchanged discipline as the single-host sync."""
    from grit_tpu.manager import watchdog  # noqa: PLC0415 — avoid cycle
    from grit_tpu.obs import progress as progress_mod  # noqa: PLC0415

    hosts: dict[str, dict] = {}
    for ordinal, job in sorted(jobs.items()):
        if job is None:
            continue
        rec = watchdog.job_progress(job)
        if rec is not None:
            hosts[str(ordinal)] = rec
    if not hosts:
        return
    etas = [h.get("etaSeconds") for h in hosts.values()]
    known_etas = [float(e) for e in etas if e is not None]
    aggregate = {
        "hosts": hosts,
        "bytesShipped": sum(int(h.get("bytesShipped") or 0)
                            for h in hosts.values()),
        "totalBytes": sum(int(h.get("totalBytes") or 0)
                          for h in hosts.values()),
        "rateBps": round(sum(float(h.get("rateBps") or 0.0)
                             for h in hosts.values()), 1),
        # The gang's ETA is its slowest host's — and unknown while ANY
        # host's is (a null ETA means that host cannot yet bound its
        # leg, so neither can the slice).
        "etaSeconds": (max(known_etas)
                       if len(known_etas) == len(etas) and known_etas
                       else None),
        "hostPairs": progress_mod.host_pair_channels(hosts.values()),
    }
    if obj.status.progress == aggregate:
        return

    def mutate(o) -> None:
        o.status.progress = aggregate

    cluster.patch(kind, obj.metadata.name, mutate, obj.metadata.namespace)
