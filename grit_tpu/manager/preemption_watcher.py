"""Preemption watcher: cloud reclaim notices fire armed standbys.

TPU-native addition (ROADMAP item 5; no reference analogue — its
migrations are operator-initiated). Spot/preemptible capacity delivers
its termination warning as a node taint (GKE:
``cloud.google.com/impending-node-termination``) seconds before the VM
dies — far too late to START a migration, exactly enough to FINISH an
armed one. This controller watches Nodes for reclaim signals and stamps
``grit.dev/fire`` on every armed StandbyCheckpoint whose source pod
lives on the reclaimed node; the checkpoint controller forwards the
annotation onto the agent Job, whose standby loop pays only the final
momentary-quiesce delta + blackout.

Detection, in priority order: any taint whose key is in
``RECLAIM_TAINT_KEYS``; the explicit ``grit.dev/preempt`` node
annotation (operators and chaos tests). Cordon (``spec.unschedulable``)
stays the drain controller's domain — it fires standbys through its own
cordon path so uncordon can also DISARM.

Reconcile is level-triggered and idempotent: firing an already-fired CR
is a no-op patch, and a node whose reclaim signal cleared before the
fire propagated simply stops producing fires (a fired standby completes
— a finished migration off a node that survived is one extra move, the
same trade the drain controller documents).
"""

from __future__ import annotations

import logging
from collections.abc import Callable

from grit_tpu.api.constants import (
    FIRE_ANNOTATION,
    PREEMPT_NODE_ANNOTATION,
    RECLAIM_TAINT_KEYS,
)
from grit_tpu.api.types import (
    Checkpoint,
    STANDBY_PRE_FIRED_PHASES,
)
from grit_tpu.kube.cluster import Cluster
from grit_tpu.kube.controller import Request, Result
from grit_tpu.obs.metrics import STANDBY_FIRES

log = logging.getLogger(__name__)


#: Prefixes of fire reasons THIS watcher mints — the checkpoint
#: controller classifies a forwarded fire's trigger by them (anything
#: it does not recognize counts as an operator fire).
RECLAIM_REASON_PREFIXES = ("NodeReclaim:", "NodePreempt:")


def reclaim_reason(node) -> str | None:
    """The node's pending-reclaim signal, or None: the first matching
    reclaim taint key, or the explicit grit.dev/preempt annotation."""
    for taint in getattr(node.spec, "taints", []) or []:
        if taint.key in RECLAIM_TAINT_KEYS:
            return f"NodeReclaim:{taint.key}"
    ann = node.metadata.annotations.get(PREEMPT_NODE_ANNOTATION, "")
    if ann:
        return f"NodePreempt:{ann}"
    return None


class PreemptionWatcher:
    # Synthetic queue keyspace: the drain controller already owns the
    # "Node" queue (ControllerManager keys queues by kind), so this
    # controller registers its own Node watch under a distinct kind —
    # and opts out of the manager's default own-kind watch (no apiserver
    # resource answers to "NodePreemption"; the REST client's watch
    # thread would die on it).
    kind = "NodePreemption"
    watch_own_kind = False

    def register(self, cluster: Cluster,
                 enqueue: Callable[[Request], None]) -> None:
        def on_node_event(ev) -> None:
            enqueue(Request("", ev.name))

        cluster.watch("Node", on_node_event)

    def reconcile(self, cluster: Cluster, req: Request) -> Result:
        node = cluster.try_get("Node", req.name, "")
        if node is None:
            return Result()
        reason = reclaim_reason(node)
        if reason is None:
            return Result()
        fired = 0
        unbound = 0
        for ckpt in cluster.list("Checkpoint"):
            if not ckpt.spec.standby:
                continue
            if ckpt.status.phase not in STANDBY_PRE_FIRED_PHASES:
                continue
            if ckpt.metadata.annotations.get(FIRE_ANNOTATION):
                continue  # already fired (idempotent re-scan)
            # status.node_name is stamped at Created→Pending; a notice
            # racing the CR's first reconcile must resolve the node from
            # the pod itself or the fire would be silently dropped.
            node_name = ckpt.status.node_name
            if not node_name:
                pod = cluster.try_get("Pod", ckpt.spec.pod_name,
                                      ckpt.metadata.namespace)
                node_name = pod.spec.node_name if pod is not None else ""
            if not node_name:
                # Fireable CR not yet bound to ANY node (pod unscheduled
                # or status lagging): re-scan shortly — the taint is
                # level state, but its watch event already fired.
                unbound += 1
                continue
            if node_name != req.name:
                continue
            self._fire(cluster, ckpt, reason)
            fired += 1
        if fired:
            log.warning(
                "preemption: node %s reclaim notice (%s) — fired %d armed "
                "standby checkpoint(s)", req.name, reason, fired)
        return Result(requeue_after=2.0) if unbound else Result()

    @staticmethod
    def _fire(cluster: Cluster, ckpt: Checkpoint, reason: str) -> None:
        def mutate(obj: Checkpoint) -> None:
            obj.metadata.annotations[FIRE_ANNOTATION] = reason

        cluster.patch("Checkpoint", ckpt.metadata.name, mutate,
                      ckpt.metadata.namespace)
        STANDBY_FIRES.inc(trigger="reclaim")
