"""Capped exponential backoff with jitter — shared retry arithmetic.

Used by the kube watch reconnect loop (a flapping apiserver must not be
hammered at a fixed 0.2 s), the manager watchdog's agent-Job re-creation
schedule, and the agent heartbeat lease. Jitter is multiplicative and
one-sided (``delay * (1 + jitter*U[0,1))``) so the floor stays the
deterministic exponential — tests can assert lower bounds exactly.
"""

from __future__ import annotations

import random
import threading
from collections.abc import Callable


def backoff_delay(
    attempt: int,
    *,
    base: float = 0.5,
    cap: float = 30.0,
    jitter: float = 0.2,
    rng: Callable[[], float] | None = None,
) -> float:
    """Delay before retry number ``attempt`` (0-based): capped
    ``base * 2**attempt``, stretched by up to ``jitter`` of itself."""
    d = min(cap, base * (2.0 ** max(0, attempt)))
    r = (rng if rng is not None else random.random)()
    return d * (1.0 + jitter * r)


class Backoff:
    """Stateful backoff for reconnect loops: ``next()`` returns the delay
    for the current consecutive-failure streak and advances it;
    ``reset()`` (call on any success) snaps back to the base."""

    def __init__(self, *, base: float = 0.2, cap: float = 30.0,
                 jitter: float = 0.2) -> None:
        self.base = base
        self.cap = cap
        self.jitter = jitter
        self._attempt = 0
        self._lock = threading.Lock()

    def next(self) -> float:
        with self._lock:
            attempt = self._attempt
            self._attempt += 1
        return backoff_delay(attempt, base=self.base, cap=self.cap,
                             jitter=self.jitter)

    def reset(self) -> None:
        with self._lock:
            self._attempt = 0

    @property
    def attempt(self) -> int:
        with self._lock:
            return self._attempt
