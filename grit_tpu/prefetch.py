"""Early restore prefetch — warm the page cache before JAX finishes importing.

The restore-side blackout decomposes as interpreter+import time, state
load, and first-step compile. The state load is disk-read-bound on a cold
destination, but the reads need nothing from JAX — so a restoring
workload can overlap them with its own imports: call
:func:`start_restore_prefetch` as its FIRST statement (this module
imports only the stdlib) and the snapshot's bytes stream into the page
cache while ``import jax`` burns CPU. By the time
``Trainer.maybe_restore_from_env`` reaches ``restore_snapshot``, reads
hit memory and the load leg is CRC/placement-bound.

Mechanism: ``posix_fadvise(WILLNEED)`` kicks off kernel readahead
asynchronously (no GIL, no copies), then a sequential read pass in a
daemon thread backstops it — pread releases the GIL, so on a 1-core host
this still overlaps with import work.

VERDICT r4 Next #4 (restart-to-state-loaded was the dominant restore
term). No reference analogue: CRIU restores memory pages itself; our
cooperative restore re-runs the workload entry point, which is what makes
this overlap window exist at all.
"""

from __future__ import annotations

import os
import threading

from grit_tpu.api import config

_READ_CHUNK = 8 << 20


def _warm_file(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        try:
            size = os.fstat(fd).st_size
            os.posix_fadvise(fd, 0, size, os.POSIX_FADV_WILLNEED)
        except (AttributeError, OSError):
            pass
        # Sequential read pass (the fadvise backstop).
        while os.read(fd, _READ_CHUNK):
            pass
    except OSError:
        pass
    finally:
        os.close(fd)


def _warm_tree(directory: str) -> None:
    for root, _dirs, files in os.walk(directory):
        for name in files:
            _warm_file(os.path.join(root, name))


def start_restore_prefetch(directory: str | None = None,
                           ) -> threading.Thread | None:
    """Begin streaming a staged snapshot into the page cache.

    ``directory`` defaults to ``$GRIT_TPU_RESTORE_DIR`` (the shim-injected
    restore annotation path). Returns the daemon thread, or None when
    there is nothing to prefetch. Never raises: a missing/unreadable dir
    simply leaves the restore path to do cold reads.
    """
    d = directory or config.TPU_RESTORE_DIR.get()
    if not d or not os.path.isdir(d):
        return None
    # This is the restored process's first executable statement — the
    # opening bracket of its interpreter+import window, which used to be
    # the biggest UNATTRIBUTED stretch of the restore-side blackout
    # (restore_snapshot closes it with restart.end). Stdlib-only import.
    from grit_tpu.obs import flight  # noqa: PLC0415

    flight.emit_near(d, "restart.start")
    # Opt-in workload-side /metrics (GRIT_WORKLOAD_METRICS_PORT): up
    # before jax even imports, so the restored pod's place/codec/tail
    # metrics are scrapeable through the whole blackout window.
    from grit_tpu.obs.server import (  # noqa: PLC0415
        start_workload_metrics_server,
    )

    start_workload_metrics_server()
    # Restored-pod logs join the gritscope timeline by uid (the flight
    # recorder context the walk-up above just established).
    from grit_tpu.obs.logctx import install_log_correlation  # noqa: PLC0415

    install_log_correlation()
    t = threading.Thread(
        target=_warm_tree, args=(d,), name="grit-restore-prefetch",
        daemon=True,
    )
    t.start()
    return t
