"""Chunk codec stage for the snapshot transport data path.

Every snapshot byte grit-tpu moves — HBM dump chunks teed to the wire or
the PVC, restore reads — historically travelled uncompressed, so transport
wall-time scaled 1:1 with state size even for highly compressible payloads
(pre-copy delta pages, optimizer state, compile-cache blobs). CRIUgpu
(arxiv 2502.16631) and PhoenixOS (arxiv 2405.12079) both report checkpoint
*transport*, not device quiesce, as the dominant migration cost at scale.
This module makes the bytes on the wire smaller and the codec work
parallel:

- three codecs — ``zstd`` (optional ``zstandard`` module), ``zlib``
  (stdlib), ``none`` (passthrough) — all GIL-releasing, so the bounded
  worker pool gives real parallelism;
- **adaptive raw-ship**: the first ``GRIT_CODEC_SAMPLE_KB`` KiB of each
  chunk are sample-compressed and the chunk ships raw when the ratio is
  poor (bf16 params usually are; delta pages and compile caches are not).
  The per-chunk decision is recorded in the transport framing (wire
  headers, container sidecar), so mixed streams restore bit-identically;
- a **container** on-disk format for the PVC streaming tee: the mirror
  data file holds concatenated (possibly compressed) block payloads and a
  ``<file>.gritc`` JSONL sidecar maps raw offsets to container offsets —
  the restore side decompresses in its read workers so decode overlaps
  the host→device place leg.

Integrity: every block/frame carries the CRC **of the raw bytes** (the
same identity the snapshot manifest records), checked after decompress —
a corrupt compressed payload can never be half-accepted, and the
snapshot's own per-chunk CRCs still verify end-to-end at restore.

This module is jax-free (the agent layer imports it) and stdlib-only
except the optional ``zstandard``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.obs.metrics import CODEC_BYTES, CODEC_QUEUE_DEPTH, CODEC_SECONDS

log = logging.getLogger(__name__)

#: Codec names as they appear in wire headers and sidecar records.
CODEC_NONE = "none"
CODEC_ZLIB = "zlib"
CODEC_ZSTD = "zstd"
#: Zero-block elision: an all-zero block ships as an EMPTY payload (the
#: record/frame carries only raw_n + CRC). Pre-copy delta chunks and
#: freshly-initialized optimizer state are dominated by zero pages —
#: CRIU's page-pipe does the same elision for process memory. Applied
#: automatically whenever a compression codec is active; never a
#: user-selectable GRIT_SNAPSHOT_CODEC value.
CODEC_ZERO = "zero"
CODECS = (CODEC_NONE, CODEC_ZLIB, CODEC_ZSTD)

#: Compression block size: chunks are split into blocks of at most this
#: many raw bytes, each compressed independently — so the worker pool
#: parallelizes *within* a multi-GB chunk, and a restore read of a small
#: raw range decompresses only the covering blocks. Matches the wire
#: frame size so one block == one frame on the migration wire.
BLOCK_BYTES = 4 * 1024 * 1024

#: Sidecar suffix of the container format ("codec journal"): a JSONL file
#: next to the container mapping raw offsets to container offsets with
#: the per-block codec decision. Presence of a (terminated) sidecar is
#: what marks a data file as a container instead of raw bytes.
SIDECAR_SUFFIX = ".gritc"
SIDECAR_FORMAT = "grit-codec-1"

# Fast levels on purpose: the codec must hide inside the transport's
# wall-clock, not add to it — ratio beyond what level 1/3 gives costs
# more compute than the saved wire time on the disks/NICs under this.
_ZLIB_LEVEL = 1
_ZSTD_LEVEL = 3


class CodecError(RuntimeError):
    """A codec operation failed or a compressed payload is corrupt
    (unknown codec id, decompressed-size mismatch, CRC-of-raw mismatch).
    Callers treat it exactly like a torn transfer: poison the journal,
    fall back loudly."""


def zstd_available() -> bool:
    try:
        import zstandard  # noqa: F401, PLC0415

        return True
    except ImportError:
        return False


_warned: set[str] = set()


def _warn_once(key: str, msg: str, *args) -> None:
    if key not in _warned:
        _warned.add(key)
        log.warning(msg, *args)


def resolve_codec(name: str | None = None) -> str:
    """The effective codec for this process: ``name`` (or
    ``GRIT_SNAPSHOT_CODEC``) validated against :data:`CODECS`, with the
    one shared degradation policy — an unknown name degrades to ``none``
    and ``zstd`` without the optional ``zstandard`` module degrades to
    ``zlib``, both with a loud (once) warning. A typo must never crash a
    data-path leg, and must never silently change what ships."""
    if name is None:
        name = str(config.SNAPSHOT_CODEC.get())
    if name not in CODECS:
        _warn_once(f"unknown:{name}",
                   "unknown snapshot codec %r; shipping uncompressed "
                   "(known: %s)", name, ", ".join(CODECS))
        return CODEC_NONE
    if name == CODEC_ZSTD and not zstd_available():
        _warn_once("nozstd",
                   "GRIT_SNAPSHOT_CODEC=zstd but the zstandard module is "
                   "not installed; degrading to zlib")
        return CODEC_ZLIB
    return name


def _compress(codec: str, view) -> bytes:
    if codec == CODEC_ZLIB:
        return zlib.compress(view, _ZLIB_LEVEL)
    if codec == CODEC_ZSTD:
        import zstandard  # noqa: PLC0415

        return zstandard.ZstdCompressor(level=_ZSTD_LEVEL).compress(
            bytes(view))
    raise CodecError(f"cannot compress with codec {codec!r}")


def _all_zero(view) -> bool:
    """memcmp-speed all-zero check, numpy-vectorized when the buffer is
    an ndarray (the dump's chunk views), bytes.count otherwise."""
    try:
        import numpy as np  # noqa: PLC0415

        if isinstance(view, np.ndarray):
            return not view.any()
    except ImportError:
        pass
    if isinstance(view, (bytes, bytearray)):
        return view.count(0) == len(view)
    return bytes(view).count(0) == len(view)


def _decompress(codec: str, payload, raw_n: int) -> bytes:
    if codec == CODEC_ZERO:
        if len(payload):
            raise CodecError(
                f"zero-elided block carries {len(payload)} payload bytes")
        return bytes(raw_n)
    if codec == CODEC_ZLIB:
        out = zlib.decompress(payload)
    elif codec == CODEC_ZSTD:
        if not zstd_available():
            raise CodecError(
                "stream carries zstd blocks but the zstandard module is "
                "not installed on the receive side")
        import zstandard  # noqa: PLC0415

        out = zstandard.ZstdDecompressor().decompress(
            bytes(payload), max_output_size=raw_n)
    else:
        raise CodecError(f"unknown codec id {codec!r}")
    return out


def decide_codec(view, codec: str, *, min_ratio: float | None = None,
                 sample_kb: int | None = None) -> str:
    """Per-CHUNK adaptive decision: sample-compress the first
    ``GRIT_CODEC_SAMPLE_KB`` KiB and return ``codec`` when the ratio
    clears ``GRIT_CODEC_MIN_RATIO``, else ``"none"`` (raw-ship). Callers
    decide once per chunk/file and pass ``presampled=True`` to
    :func:`compress_block` for its blocks — bf16 weights pay one few-KiB
    sample per multi-MB chunk, not one per block."""
    if codec == CODEC_NONE or len(view) == 0:
        return CODEC_NONE
    if min_ratio is None:
        min_ratio = float(config.CODEC_MIN_RATIO.get())
    if sample_kb is None:
        sample_kb = int(config.CODEC_SAMPLE_KB.get())
    sample_n = min(len(view), max(1, sample_kb) * 1024)
    t0 = time.monotonic()
    # Head AND mid samples, BOTH must clear the ratio: a chunk whose
    # entropy is concentrated at one end (delta islands) must not drag
    # its incompressible half through a full compression pass — the
    # conservative raw decision costs nothing, because all-zero blocks
    # are still elided per block regardless of this decision.
    ok = True
    for start in {0, max(0, (len(view) - sample_n) // 2)}:
        sample = _compress(codec, view[start:start + sample_n])
        if len(sample) / sample_n > min_ratio:
            ok = False
            break
    CODEC_SECONDS.inc(time.monotonic() - t0, dir="compress")
    # No byte accounting here: the raw-shipped bytes are counted per
    # BLOCK in compress_block (its elide_zeros early-return), so the
    # mirror and send_file transports account identically.
    return codec if ok else CODEC_NONE


def compress_block(view, codec: str, *, min_ratio: float | None = None,
                   sample_kb: int | None = None,
                   presampled: bool = False,
                   elide_zeros: bool = False):
    """One block through the codec stage, adaptively.

    Returns ``(codec_used, payload, raw_n, crc_raw)``. ``codec_used`` is
    ``"zero"`` (empty payload) for an all-zero block, ``"none"``
    (payload is ``view`` itself — zero copy) when compression is off,
    the sample ratio is poor, or the full compression failed to beat
    raw. ``presampled=True`` skips the per-block head sample (the caller
    already ran :func:`decide_codec` on the whole chunk).
    ``elide_zeros=True`` applies zero-block elision even when ``codec``
    is ``"none"`` — passed by transport paths for raw-DECIDED chunks of
    a codec-enabled stream, never in plain passthrough mode (where the
    tee must stay byte-identical raw). ``crc_raw`` is always the zlib
    CRC32 of the *raw* bytes — the end-to-end identity both transport
    and manifest agree on.
    """
    faults.fault_point("codec.compress", wrap=CodecError)
    raw_n = len(view)
    crc_raw = zlib.crc32(view) & 0xFFFFFFFF
    if raw_n and (codec != CODEC_NONE or elide_zeros) \
            and _all_zero(view):
        # Zero-block elision: no payload at all. Cheaper than any codec
        # (one vectorized scan) and exactly the shape pre-copy delta
        # chunks have — mostly-unchanged state whose changed rows are
        # sparse islands in zero pages. Applies regardless of the
        # chunk-level sample decision.
        CODEC_BYTES.inc(raw_n, dir="compress_in", codec=CODEC_ZERO)
        return CODEC_ZERO, b"", raw_n, crc_raw
    if codec == CODEC_NONE or raw_n == 0:
        if elide_zeros and raw_n:
            # A raw-DECIDED block of a codec-enabled stream (the chunk/
            # file sampler said raw): count it here so every transport
            # accounts the full raw-shipped byte volume, not just the
            # sampled head.
            CODEC_BYTES.inc(raw_n, dir="compress_raw_shipped",
                            codec=CODEC_NONE)
        return CODEC_NONE, view, raw_n, crc_raw
    if min_ratio is None:
        min_ratio = float(config.CODEC_MIN_RATIO.get())
    if sample_kb is None:
        sample_kb = int(config.CODEC_SAMPLE_KB.get())
    t0 = time.monotonic()
    sample_n = min(raw_n, max(1, sample_kb) * 1024)
    if not presampled and sample_n < raw_n:
        # Sample-decide: compress the head; incompressible chunks (bf16
        # weights) bail after a few KiB instead of paying a full pass
        # that saves nothing on the wire.
        sample = _compress(codec, view[:sample_n])
        if len(sample) / sample_n > min_ratio:
            CODEC_SECONDS.inc(time.monotonic() - t0, dir="compress")
            CODEC_BYTES.inc(raw_n, dir="compress_raw_shipped", codec=codec)
            return CODEC_NONE, view, raw_n, crc_raw
    payload = _compress(codec, view)
    CODEC_SECONDS.inc(time.monotonic() - t0, dir="compress")
    if len(payload) / raw_n > min_ratio:
        # The sample lied (or the whole chunk fit in the sample): raw
        # still ships — the decision is recorded per block either way.
        CODEC_BYTES.inc(raw_n, dir="compress_raw_shipped", codec=codec)
        return CODEC_NONE, view, raw_n, crc_raw
    CODEC_BYTES.inc(raw_n, dir="compress_in", codec=codec)
    CODEC_BYTES.inc(len(payload), dir="compress_out", codec=codec)
    return codec, payload, raw_n, crc_raw


def decompress_block(codec: str, payload, raw_n: int,
                     crc_raw: int | None = None) -> bytes:
    """Inverse of :func:`compress_block` for one block/frame; validates
    the codec id, the declared raw size, and (when given) the CRC of the
    raw bytes. Raises :class:`CodecError` on any mismatch — a corrupt
    compressed payload must fail the leg, never land half-decoded."""
    faults.fault_point("codec.decompress", wrap=CodecError)
    if codec == CODEC_NONE:
        raw = payload
    else:
        t0 = time.monotonic()
        try:
            raw = _decompress(codec, payload, raw_n)
        except (zlib.error, ValueError, MemoryError) as exc:
            # zstandard raises ZstdError (a subclass of Exception defined
            # in the optional module) — normalize through its message.
            raise CodecError(f"decompress({codec}) failed: {exc}") from exc
        except Exception as exc:  # zstandard.ZstdError, not importable here
            if type(exc).__name__ != "ZstdError":
                raise
            raise CodecError(f"decompress({codec}) failed: {exc}") from exc
        CODEC_SECONDS.inc(time.monotonic() - t0, dir="decompress")
        CODEC_BYTES.inc(len(payload), dir="decompress_in", codec=codec)
        CODEC_BYTES.inc(len(raw), dir="decompress_out", codec=codec)
    if len(raw) != raw_n:
        raise CodecError(
            f"decompressed size mismatch: got {len(raw)}, header says "
            f"{raw_n} ({codec})")
    if crc_raw is not None and (zlib.crc32(raw) & 0xFFFFFFFF) != crc_raw:
        raise CodecError(
            f"CRC-of-raw mismatch after {codec} decompress "
            "(corrupt in transit)")
    return raw


# -- bounded worker pool ------------------------------------------------------

_pool_lock = threading.Lock()
_pool: ThreadPoolExecutor | None = None
_pool_workers = 0
# Jobs currently EXECUTING in the pool (picked up, not finished):
# together with the queue depth this gives pool saturation — the
# "is the codec the bottleneck" number the profiling ledger publishes.
_active_lock = threading.Lock()
_active_jobs = 0


def workers() -> int:
    """Codec worker count: ``GRIT_CODEC_WORKERS`` when set (clamped to
    >=1), else core-derived — the codec must saturate neither the dump's
    host cores nor a single thread."""
    configured = int(config.CODEC_WORKERS.get())
    if configured != config.CODEC_WORKERS.default:
        return max(1, configured)
    try:
        cores = os.cpu_count() or 1
    except Exception:
        cores = 1
    return max(2, min(8, cores))


def shared_pool() -> ThreadPoolExecutor:
    """The process-wide codec pool (compress on the dump side, decode +
    CRC verify on the receive side). Bounded by :func:`workers`; callers
    bound their in-flight submissions themselves (byte budget on the
    mirror queue, a semaphore on the wire receiver)."""
    global _pool, _pool_workers
    want = workers()
    with _pool_lock:
        if _pool is None or _pool_workers != want:
            # Tests flip GRIT_CODEC_WORKERS: re-size by replacing (the
            # old pool drains its queue and exits its idle threads).
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = ThreadPoolExecutor(
                max_workers=want, thread_name_prefix="grit-codec")
            _pool_workers = want
        return _pool


def queue_depth() -> int | None:
    """Jobs queued (not yet picked up) in the shared pool right now;
    None when no pool has been created — reading must never create one."""
    with _pool_lock:
        pool = _pool
    if pool is None:
        return None
    try:
        return pool._work_queue.qsize()
    except AttributeError:  # executor internals changed
        return None


def active_jobs() -> int:
    """Codec jobs executing right now (submitted through
    :func:`pool_submit` and picked up by a worker)."""
    with _active_lock:
        return _active_jobs


def pool_saturation() -> float | None:
    """(active + queued jobs) / pool workers, or None when no pool has
    ever been created. 0 = idle, 1 = every worker busy, >1 = a backlog
    is queued behind busy workers — the codec stage, not the transport,
    paces the data path."""
    with _pool_lock:
        pool, nworkers = _pool, _pool_workers
    if pool is None:
        return None
    try:
        queued = pool._work_queue.qsize()
    except AttributeError:  # executor internals changed
        queued = 0
    return (active_jobs() + queued) / max(1, nworkers)


def sample_queue_depth() -> None:
    """Periodic-sampler refresh of ``grit_codec_queue_depth``: the
    per-submission edge write below goes stale the moment workers drain
    the backlog, so scrapes between submissions used to read a historical
    depth. The sampler re-derives it from the live queue."""
    depth = queue_depth()
    if depth is not None:
        CODEC_QUEUE_DEPTH.set(depth)


def pool_submit(fn, *args, **kwargs):
    """Submit ``fn`` to the shared pool through the two cross-cutting
    seams every submission needs:

    - **trace context**: the submitting thread's span context rides along
      (``trace.wrap_parented``), so spans/record_spans emitted inside the
      worker join the migration trace instead of rooting their own — the
      thread-local parent used to be lost at the pool boundary;
    - **queue-depth gauge**: ``grit_codec_queue_depth`` samples the
      pool's backlog at submission, making "the codec is the bottleneck"
      visible without a profiler.
    """
    from grit_tpu.obs import trace  # noqa: PLC0415

    pool = shared_pool()
    wrapped = trace.wrap_parented(fn)

    def _counted(*a, **k):
        global _active_jobs
        with _active_lock:
            _active_jobs += 1
        try:
            return wrapped(*a, **k)
        finally:
            with _active_lock:
                _active_jobs -= 1

    fut = pool.submit(_counted, *args, **kwargs)
    try:
        CODEC_QUEUE_DEPTH.set(pool._work_queue.qsize())
    except AttributeError:  # executor internals changed: gauge is optional
        pass
    return fut


# -- container format (PVC streaming tee at rest) -----------------------------


@dataclass(frozen=True)
class BlockRecord:
    codec: str
    raw_off: int
    raw_n: int
    comp_off: int
    comp_n: int
    crc_raw: int


@dataclass
class ContainerIndex:
    """Parsed ``.gritc`` sidecar: the raw→container offset map."""

    raw_size: int
    comp_size: int
    records: list[BlockRecord]

    def covering(self, offset: int, nbytes: int) -> list[BlockRecord]:
        """Records overlapping raw range ``[offset, offset+nbytes)`` in
        raw-offset order. Raises :class:`CodecError` when the range is
        not fully covered (a torn sidecar/container)."""
        want_end = offset + nbytes
        out = [r for r in self.records
               if r.raw_off < want_end and r.raw_off + r.raw_n > offset]
        covered = offset
        for r in sorted(out, key=lambda r: r.raw_off):
            if r.raw_off > covered:
                break
            covered = max(covered, r.raw_off + r.raw_n)
        if covered < want_end:
            raise CodecError(
                f"container does not cover raw bytes "
                f"[{offset}, {want_end}) (have up to {covered})")
        return sorted(out, key=lambda r: r.raw_off)


class SidecarWriter:
    """Streaming writer of the container's ``.gritc`` sidecar. One JSON
    line per block, flushed as written (a crash leaves an unterminated —
    therefore invalid — sidecar, never a silently-short valid one); the
    terminal line seals it with the totals readers trust."""

    def __init__(self, container_path: str) -> None:
        self.path = container_path + SIDECAR_SUFFIX
        self._f = open(self.path, "w")
        self._f.write(json.dumps(
            {"format": SIDECAR_FORMAT,
             "file": os.path.basename(container_path)}) + "\n")
        self.records = 0

    def record(self, codec: str, raw_off: int, raw_n: int,
               comp_off: int, comp_n: int, crc_raw: int) -> None:
        self._f.write(json.dumps(
            {"c": codec, "ro": raw_off, "rn": raw_n,
             "co": comp_off, "cn": comp_n, "crc": crc_raw}) + "\n")
        self._f.flush()
        self.records += 1

    def close(self, raw_size: int, comp_size: int) -> None:
        self._f.write(json.dumps(
            {"done": True, "raw_size": raw_size, "comp_size": comp_size,
             "records": self.records}) + "\n")
        self._f.flush()
        self._f.close()

    def abandon(self) -> None:
        try:
            self._f.close()
            os.unlink(self.path)
        except OSError:
            pass


# Sidecars are immutable once terminated — cache parsed indexes on the
# (size, mtime) identity so the restore pipeline's per-chunk reads do not
# re-parse a thousand-line sidecar a thousand times.
_index_lock = threading.Lock()
_index_cache: dict[str, tuple[tuple[int, int], ContainerIndex]] = {}


def load_container_index(data_path: str) -> ContainerIndex | None:
    """The :class:`ContainerIndex` for ``data_path`` when a terminated
    sidecar sits next to it; ``None`` when the file is plain raw bytes
    (no sidecar). An existing but unterminated/malformed sidecar raises
    :class:`CodecError` — that is a torn transfer, not a raw file."""
    sidecar = data_path + SIDECAR_SUFFIX
    try:
        st = os.stat(sidecar)
    except OSError:
        return None
    token = (st.st_size, st.st_mtime_ns)
    with _index_lock:
        hit = _index_cache.get(sidecar)
        if hit is not None and hit[0] == token:
            return hit[1]
    records: list[BlockRecord] = []
    raw_size = comp_size = -1
    try:
        with open(sidecar) as f:
            header = json.loads(f.readline())
            if header.get("format") != SIDECAR_FORMAT:
                raise CodecError(
                    f"{sidecar}: unknown sidecar format "
                    f"{header.get('format')!r}")
            for line in f:
                if not line.strip():
                    continue
                rec = json.loads(line)
                if rec.get("done"):
                    raw_size = int(rec["raw_size"])
                    comp_size = int(rec["comp_size"])
                    break
                records.append(BlockRecord(
                    codec=str(rec["c"]), raw_off=int(rec["ro"]),
                    raw_n=int(rec["rn"]), comp_off=int(rec["co"]),
                    comp_n=int(rec["cn"]), crc_raw=int(rec["crc"])))
    except (OSError, ValueError, KeyError) as exc:
        raise CodecError(f"{sidecar}: malformed codec sidecar: {exc}")
    if raw_size < 0:
        raise CodecError(
            f"{sidecar}: sidecar has no terminal line — container is "
            "torn or still being written")
    index = ContainerIndex(raw_size=raw_size, comp_size=comp_size,
                           records=records)
    with _index_lock:
        if len(_index_cache) >= 64:
            # The cache only needs to serve one restore's repeated chunk
            # reads; unbounded retention across weeks of migrations on a
            # long-lived agent is a slow leak. Rebuilding is cheap.
            _index_cache.clear()
        _index_cache[sidecar] = (token, index)
    return index


def container_elided_fraction(data_path: str) -> float | None:
    """Fraction of ``data_path``'s raw payload bytes that shipped as
    zero-elided blocks (empty payloads), or None when the file is not a
    terminated container. The serving KV-cache evidence number: a
    half-empty batch grid whose free-slot pages were tagged (zeroed)
    before the dump should see most of its cache bytes elide here —
    and a regression back to dense shipping reads as ~0.0."""
    try:
        idx = load_container_index(data_path)
    except CodecError:
        return None
    if idx is None or idx.raw_size <= 0:
        return None
    elided = sum(r.raw_n for r in idx.records if r.codec == CODEC_ZERO)
    return elided / idx.raw_size


def container_raw_size(data_path: str) -> int | None:
    """Raw payload size a container at ``data_path`` decodes to, or None
    when it is not a (valid, terminated) container. Size checks against
    commit maps / skip captures compare raw identities through this."""
    try:
        idx = load_container_index(data_path)
    except CodecError:
        return None
    return idx.raw_size if idx is not None else None


# Flight-event dedupe for the loud degrade: one io.degrade event per
# (reason, directory) per process — the metric still counts every
# degraded read, the log warns once per reason (native.file).
_degrade_marked: set[tuple[str, str]] = set()
_degrade_lock = threading.Lock()


def note_native_degrade(reason: str, near_path: str) -> None:
    """The loud half of the native file plane's degrade contract: count
    it (grit_io_degrade_total), log it once, and stamp an ``io.degrade``
    flight event on the migration timeline governing ``near_path``."""
    from grit_tpu.native import file as native_file  # noqa: PLC0415
    from grit_tpu.obs import flight  # noqa: PLC0415

    native_file.record_degrade(reason)
    d = os.path.dirname(os.path.abspath(near_path))
    with _degrade_lock:
        if (reason, d) in _degrade_marked:
            return
        _degrade_marked.add((reason, d))
    flight.emit_near(d, "io.degrade", reason=reason, plane="file")


def native_container_range(data_path: str, index: ContainerIndex,
                           offset: int, nbytes: int, *, recs=None,
                           verify_algo: str | None = None):
    """Native (gritio-file) decode of a container range: the covering
    blocks batch-read (io_uring/preadv), decoded, per-block
    CRC-verified and assembled into one buffer in a single GIL-released
    call — the PVC codec leg without the Python pool round-trip.

    Returns ``(uint8 ndarray, crc_of_range_or_None)`` where the crc is
    per ``verify_algo`` ("crc32"|"crc32c"), or ``None`` when the native
    plane is unavailable or degraded — the degrade is LOUD
    (:func:`note_native_degrade`), never silent. Corrupt data raises
    :class:`CodecError` exactly like the Python decode (the bytes are
    bad on disk; retrying them on the Python plane would fail the same
    way)."""
    from grit_tpu import faults as _faults  # noqa: PLC0415
    from grit_tpu.native import file as native_file  # noqa: PLC0415

    if not native_file.enabled():
        reason = native_file.unavailable_reason()
        if reason is not None:
            note_native_degrade(reason, data_path)
        return None
    if recs is None:
        recs = index.covering(offset, nbytes)
    if any(r.codec not in (CODEC_NONE, CODEC_ZLIB, CODEC_ZERO)
           for r in recs):
        # zstd blocks: the optional Python module owns that codec.
        note_native_degrade("zstd", data_path)
        return None
    try:
        _faults.fault_point("io.place")
        return native_file.place_container(
            data_path, recs, offset, nbytes, verify_algo=verify_algo)
    except _faults.FaultInjected:
        note_native_degrade("fault", data_path)
        return None
    except native_file.NativeDataError as exc:
        raise CodecError(
            f"native container decode failed in {data_path}@{offset}: "
            f"{exc}") from exc
    except (native_file.NativePlaneError, OSError) as exc:
        note_native_degrade("error", data_path)
        log.warning("native place failed for %s@%s (%s); Python plane "
                    "takes this read", data_path, offset, exc)
        return None


def read_container_range(data_path: str, index: ContainerIndex,
                         offset: int, nbytes: int,
                         pread=None) -> bytes:
    """Raw bytes ``[offset, offset+nbytes)`` of the container's payload,
    decoding only the covering blocks. ``pread(comp_off, comp_n)`` reads
    container bytes (injectable so the restore pipeline can gate each
    read on its staging waterline); defaults to a plain file pread.

    With no injected ``pread``, the native file plane
    (:func:`native_container_range`) takes the read when available; the
    Python block loop below is the loud-degrade fallback and the gated
    (journal-streamed) path."""
    if pread is None:
        native = native_container_range(data_path, index, offset, nbytes)
        if native is not None:
            # One copy to honor this convenience API's bytes contract;
            # the restore hot path (_read_chunk_container) consumes the
            # ndarray zero-copy via native_container_range directly.
            return native[0].tobytes()
    out = bytearray(nbytes)
    f = None
    if pread is None:
        f = open(data_path, "rb")

        def pread(co: int, cn: int) -> bytes:  # noqa: PLR0917
            f.seek(co)
            return f.read(cn)
    try:
        for rec in index.covering(offset, nbytes):
            payload = pread(rec.comp_off, rec.comp_n)
            if len(payload) != rec.comp_n:
                raise CodecError(
                    f"short container read at {rec.comp_off} "
                    f"({len(payload)}/{rec.comp_n})")
            raw = decompress_block(rec.codec, payload, rec.raw_n,
                                   rec.crc_raw)
            lo = max(offset, rec.raw_off)
            hi = min(offset + nbytes, rec.raw_off + rec.raw_n)
            out[lo - offset:hi - offset] = \
                memoryview(raw)[lo - rec.raw_off:hi - rec.raw_off]
    finally:
        if f is not None:
            f.close()
    return bytes(out)
