"""grit_tpu — TPU-native transparent checkpoint/restore and live migration
for Kubernetes pods running JAX/XLA workloads.

A ground-up re-architecture of the capability set of fossabot/grit
(reference: GRIT, a Go/Kubernetes system for CUDA pod checkpoint/restore via
CRIU + cuda-checkpoint). This build replaces the NVIDIA device path with a
TPU-native one:

- control plane: ``Checkpoint``/``Restore`` resources driven by phase state
  machines (:mod:`grit_tpu.manager`), mirroring the reference's
  ``pkg/gritmanager`` behaviorally.
- node agent: checkpoint/restore data mover (:mod:`grit_tpu.agent`),
  mirroring ``pkg/gritagent``.
- runtime integration: shim + CRI interceptor logic (:mod:`grit_tpu.runtime`),
  mirroring ``cmd/containerd-shim-grit-v1`` + ``contrib/containerd``.
- device layer (all-new, TPU-native): XLA:TPU quiesce + HBM snapshot engine
  (:mod:`grit_tpu.device`), replacing CRIU's ``cuda_plugin.so`` +
  ``cuda-checkpoint``.
- slice coordination (all-new): multi-host barrier/mesh re-init
  (:mod:`grit_tpu.parallel`) — the reference is single-GPU scoped and has no
  equivalent (SURVEY §2.4).
"""

__version__ = "0.1.0"
