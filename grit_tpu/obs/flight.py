"""Per-migration flight recorder: crash-safe phase-boundary event log.

The product of this system is a latency budget (<60 s blackout), yet the
existing spans and metrics are per-process: nothing reconstructs ONE
migration end-to-end across manager, source agent, destination agent and
the device layer, so the blackout machinery cannot be decomposed — and a
blackout you cannot decompose you cannot shrink (CRIUgpu's evaluation is
exactly this phase-timing breakdown; PAPERS.md). This module is the
instrumentation floor the ROADMAP's pre-copy-convergence and multi-host
items stand on:

- **One append-only JSONL file per migration**, keyed by the Checkpoint
  uid (default: the checkpoint-name basename of the work/stage dir — the
  same key on both ends of a migration), written into the agent's
  work/stage dir next to the PR-3 termination-reason file
  (:data:`grit_tpu.metadata.FLIGHT_LOG_FILE`). The file is node-local
  observability and is excluded from every transfer/wire tree walk — it
  never ships with the checkpoint.
- **Crash-safe by construction**: every event is one ``O_APPEND`` write
  of one JSON line; phase-boundary events (``*.start``/``*.end``/opens/
  commits/fails) additionally fsync, so an agent SIGKILL mid-migration
  still yields a readable partial timeline. Readers skip a torn trailing
  line; the analyzer (``tools/gritscope``) marks the gap.
- **Every event carries wall AND monotonic timestamps** plus host/pid/
  role. Cross-process alignment: each process's wall/mono pair set gives
  its mono→wall offset; the wire commit handshake additionally exchanges
  explicit clock pairs (``clock.peer`` events on both ends) and the
  manager stamps its own pair into agent Jobs (``GRIT_FLIGHT_CLOCK`` →
  ``clock.manager``), so ``gritscope`` can estimate inter-host skew.
- **Event names are a closed registry** (:data:`EVENTS`), enforced both
  ways by the ``flight-events`` gritlint rule: every emit site uses a
  declared literal name, every declared name has an emit site, and the
  ``gritscope`` phase model references only declared events. Dynamic
  event names are rejected — the registry is the contract.

Recording is off unless ``GRIT_FLIGHT`` is set (observability must never
tax the data path by default); the chaos/obs lanes and bench enable it.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any

from grit_tpu.api import config
from grit_tpu.metadata import FLIGHT_LOG_FILE
from grit_tpu.obs import profile
from grit_tpu.obs.metrics import FLIGHT_EVENTS

log = logging.getLogger(__name__)

#: Canonical registry of every flight event the tree emits, grouped by
#: phase family (the first dotted segment — also the bounded label of
#: ``grit_flight_events_total``). The ``flight-events`` lint rule keeps
#: this registry, the emit sites, and the gritscope phase model
#: (``tools/gritscope/phases.py``) agreeing in both directions.
EVENTS = (
    # lifecycle / clock alignment
    "migration.configure",
    "clock.manager",
    "clock.peer",
    # source: the agent's whole blackout leg (enclosing, lowest-priority
    # phase: glue between the named phases attributes here, not to a gap)
    "source.start",
    "source.end",
    # source: quiesce + device dump
    "quiesce.start",
    "quiesce.end",
    "dump.start",
    "dump.chunk",
    "dump.end",
    # speculative (quiesce-free) dump: the concurrent pass launched at
    # the quiesce REQUEST (before the park) and the validation decision
    # at the step boundary — the bracket gritscope attributes as
    # dump_concurrent, showing the dump overlapping execution instead
    # of sitting inside the blackout window
    "snap.speculative.start",
    "snap.speculative.validated",
    "precopy.start",
    "precopy.end",
    # one bracket per convergence-loop round (round 0 = the full pass)
    "precopy.round.start",
    "precopy.round.end",
    # standby mode: one bracket per governed delta round (round 0 = the
    # arming full pass), plus the instant the arm/fire protocol fired
    "standby.round.start",
    "standby.round.end",
    "standby.fire",
    # source: process (CRIU) dump + transport
    "criu.dump.start",
    "criu.dump.end",
    "upload.start",
    "upload.end",
    "wire.open",
    "wire.send.start",
    "wire.send.end",
    "wire.commit.start",
    "wire.commit.end",
    "wire.close",
    # destination: receive + stage + restore
    "wire.recv.open",
    "wire.recv.commit",
    "wire.recv.fail",
    "stage.start",
    "stage.end",
    # restored process: interpreter+import window (prefetch opens it as
    # the process's first statement; restore_snapshot closes it)
    "restart.start",
    "restart.end",
    "criu.restore.start",
    "criu.restore.end",
    "place.start",
    "place.waterline",
    "place.end",
    # post-copy restore: the cold-array tail placed AFTER the workload
    # resumed (blackout ends at "hot set placed", the tail overlaps the
    # restart/compile window and first-touch blocks per array)
    "postcopy.tail.start",
    "postcopy.tail.end",
    # codec stage
    "codec.wait",
    # native file data plane (gritio-file): one summary point per leg —
    # io.drain when a dump's mirror tee ran the native drain (raw/comp
    # bytes, wall), io.place when a restore's container/raw reads went
    # through the native place path (bytes, read engine), io.degrade
    # whenever a leg that WOULD have run native fell back to the Python
    # plane (reason) — the loud half of the degrade contract.
    "io.drain",
    "io.place",
    "io.degrade",
    # gang slice migration (grit_tpu.agent.slicerole + coordination):
    # the cross-host quiesce barrier bracket (per host: from "reached
    # the agreed cut step" to "every host arrived"), the instant a
    # destination leg verified and parked prepared, and the slice-wide
    # commit/abort decisions any host may record in the shared ledger
    "slice.barrier.start",
    "slice.barrier.end",
    "slice.prepared",
    "slice.commit",
    "slice.abort",
    # resume / recovery
    "resume.start",
    "resume.end",
    "abort.start",
    "abort.end",
    # manager control plane
    "manager.phase",
    "manager.abort",
    # fleet migration scheduler (grit_tpu.manager.fleet): plan-level
    # decisions keyed by the PLAN name as uid — phase/verdict moves,
    # each bin-packing placement, each admission wave advancing, and
    # each member failure resolution (retry vs recorded-failed)
    "fleet.plan",
    "fleet.place",
    "fleet.wave",
    "fleet.abort",
    # serving snapshot fan-out (grit_tpu.serving + the RestoreSet
    # controller): the request-drain bracket the serving agentlet runs
    # before parking at a batch boundary (per drain: policy, slots
    # drained vs serialized), the fan-out decision keyed by the
    # SNAPSHOT name as uid, and per-clone lifecycle points (created /
    # first served while the cold tail was still in flight / ready /
    # aborted) from both the controller and the in-process fan-out legs
    "serve.drain.start",
    "serve.drain.end",
    "serve.fanout",
    "serve.clone.start",
    "serve.clone.served",
    "serve.clone.ready",
    "serve.clone.abort",
)

_EVENT_SET = frozenset(EVENTS)

#: High-rate waterline/progress events: flushed, not fsynced (a lost
#: trailing waterline costs resolution, not the timeline).
_NO_FSYNC = frozenset(("dump.chunk", "place.waterline", "codec.wait",
                       "manager.phase"))

_lock = threading.Lock()
_recorder: "Recorder | None" = None
#: The recorder the most recent emission actually used. Differs from
#: the configured one in processes that never call configure() — the
#: workload's agentlet and the restored pod join the migration via
#: emit_near's walk-up. Log correlation reads this so THOSE processes'
#: lines carry the uid too.
_last_active: "Recorder | None" = None
#: dir → Recorder (or None): walk-up results cached as OBJECTS so the
#: hot emit_near events (dump.chunk per HBM chunk) pay a dict hit, not
#: a Recorder construction (env read + path normalization) per event.
_near_cache: dict[str, "Recorder | None"] = {}
_warned_unknown: set[str] = set()
# Cached once: a gethostname() syscall per event would tax the exact
# blackout window the recorder measures (dump.chunk fires per chunk).
_HOST = socket.gethostname()


def enabled() -> bool:
    """Flight recording is opt-in (``GRIT_FLIGHT``): emit sites are one
    env read when off, exactly like trace/faults."""
    return bool(config.FLIGHT.get())


class Recorder:
    """One migration's flight log. Stateless between events on purpose:
    each emit is an independent ``open(append) → write one line →
    [fsync] → close`` so concurrent processes (agent, workload agentlet,
    shim) can append to the same file safely (single-``write`` O_APPEND
    lines), and a crashed writer never wedges a shared handle."""

    def __init__(self, path: str, uid: str, role: str) -> None:
        self.path = path
        self.uid = uid
        self.role = role
        # Tee target resolved ONCE (env read + path normalization are
        # per-event costs otherwise; the env is stable for a process).
        self._tee: str | None = None
        tee_dir = str(config.FLIGHT_DIR.get())
        if tee_dir:
            tee = os.path.join(
                tee_dir, f"flight-{_HOST}-{os.getpid()}.jsonl")
            if os.path.abspath(tee) != os.path.abspath(path):
                try:
                    os.makedirs(tee_dir, exist_ok=True)
                    self._tee = tee
                except OSError:
                    self._tee = None

    def write(self, event: str, durable: bool,
              fields: dict[str, Any]) -> None:
        record = {
            "ev": event,
            "uid": self.uid,
            "role": self.role,
            "wall": time.time(),
            "mono": time.monotonic(),
            "host": _HOST,
            "pid": os.getpid(),
        }
        record.update(fields)
        line = json.dumps(record, default=str) + "\n"
        try:
            self._append(self.path, line, durable)
        except OSError as exc:
            # Observability must never take down the data path.
            log.warning("flight log %s unwritable: %s", self.path, exc)
        if self._tee is not None:
            # Lane artifact tee: one file per process so concurrent test
            # migrations do not interleave partial lines across hosts.
            try:
                self._append(self._tee, line, False)
            except OSError:
                pass

    @staticmethod
    def _append(path: str, line: str, durable: bool) -> None:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line.encode())
            if durable:
                os.fsync(fd)
        finally:
            os.close(fd)


def _default_uid(dir_path: str) -> str:
    return os.path.basename(os.path.normpath(dir_path)) or "migration"


def configure(dir_path: str, role: str, uid: str | None = None) -> None:
    """Open (or adopt) the migration's flight log in ``dir_path`` and make
    it this process's default sink. Called by the agent drivers at entry
    (checkpoint/restore/abort); a no-op when ``GRIT_FLIGHT`` is off.

    Emits ``migration.configure`` (the recorder's own clock pair — the
    anchor every later event aligns against) and, when the manager
    stamped its pair into this Job's env (``GRIT_FLIGHT_CLOCK``), a
    ``clock.manager`` event echoing it so manager-side events can be
    placed on the agent timeline."""
    global _recorder
    if not enabled():
        return
    try:
        os.makedirs(dir_path, exist_ok=True)
    except OSError as exc:
        log.warning("flight: cannot create %s: %s", dir_path, exc)
        return
    path = os.path.join(dir_path, FLIGHT_LOG_FILE)
    with _lock:
        _recorder = Recorder(path, uid or _default_uid(dir_path), role)
        _near_cache.clear()
    emit("migration.configure", dir=dir_path)
    raw_clock = str(config.FLIGHT_CLOCK.get())
    if raw_clock:
        try:
            pair = json.loads(raw_clock)
            emit("clock.manager",
                 peer_wall=float(pair.get("wall", 0.0)),
                 peer_mono=float(pair.get("mono", 0.0)),
                 peer_host=str(pair.get("host", "")),
                 peer_pid=int(pair.get("pid", 0)))
        except (ValueError, TypeError):
            log.warning("flight: malformed %s=%r ignored",
                        config.FLIGHT_CLOCK.name, raw_clock)


def clock_pair() -> dict[str, Any]:
    """This process's wall/monotonic pair, for handshake exchange (the
    wire commit/ack and the manager's Job stamp both carry one)."""
    return {"wall": time.time(), "mono": time.monotonic(),
            "host": socket.gethostname(), "pid": os.getpid()}


def current() -> "Recorder | None":
    with _lock:
        return _recorder


def active() -> "Recorder | None":
    """The configured recorder, or — in processes that never ran
    configure() (workload agentlet, restored pod) — the recorder the
    most recent emission resolved to. The migration context for log
    correlation."""
    with _lock:
        return _recorder or _last_active


def reset() -> None:
    """Forget the configured recorder (tests)."""
    global _recorder, _last_active
    with _lock:
        _recorder = None
        _last_active = None
        _near_cache.clear()


def emit(event: str, dir: str | None = None, **fields: object) -> None:  # noqa: A002
    """Record one event on the configured recorder (or, with ``dir``, on
    the flight log governing that directory — see :func:`emit_near` for
    the lookup). Cheap no-op when recording is off; unknown event names
    are dropped with a loud (once) warning — the ``flight-events`` lint
    catches them statically, and a typo at runtime must not crash a
    data-path leg."""
    if not enabled():
        return
    if event not in _EVENT_SET:
        if event not in _warned_unknown:
            _warned_unknown.add(event)
            log.warning("flight: undeclared event %r dropped "
                        "(register it in grit_tpu.obs.flight.EVENTS)",
                        event)
        return
    # Priority: a dir-carrying event belongs to the log governing that
    # dir (source and destination drivers can share one process — the
    # harness does — and the module-global recorder then points at
    # whichever configured last); then the configured recorder; then the
    # artifact-dir fallback (processes with no work/stage dir — the
    # manager; gritscope merges by the uid the event carries).
    rec = _resolve(dir) or _dir_recorder()
    if rec is None:
        return
    family = event.split(".", 1)[0]
    FLIGHT_EVENTS.inc(phase=family)
    rec.write(event, event not in _NO_FSYNC, fields)
    # Phase brackets arm/disarm the phase-scoped profiler (a dict miss
    # for every non-boundary event; profile guards itself — it must
    # never take down the leg that emitted the event).
    profile.on_flight_event(rec, event)


def emit_near(dir_path: str, event: str, **fields: object) -> None:
    """Emit onto the flight log that governs ``dir_path`` — found by
    walking up a bounded number of parents, exactly like the stage
    journal's ``_StageMonitor.find``. This is how processes that never
    ran :func:`configure` (the workload's agentlet dump, the restored
    workload's place loop, the shim) join the migration's log: the
    driver created the file at the work/stage root, and the device dirs
    live a few levels below it. No file found → recording is off for
    this dir (never create stray files inside snapshot trees).

    Deliberately NOT gated on ``GRIT_FLIGHT``: the emitting process is
    often a workload pod whose environment predates the migration (a
    running pod cannot be re-env'd, and a restored pod inherits the
    pre-dump env). The per-migration log file IS the enablement signal —
    the driver only creates it when flight recording is on, and the
    walk-up is one cached stat when it is off."""
    rec = _resolve(dir_path)
    if rec is None:
        return
    emit_on(rec, event, **fields)


def emit_on(rec: Recorder, event: str, **fields: object) -> None:
    global _last_active
    if rec is None:
        return
    with _lock:
        _last_active = rec
    if event not in _EVENT_SET:
        # Warn directly: emit()'s funnel is env-gated, and this path
        # serves exactly the processes whose env predates the migration.
        if event not in _warned_unknown:
            _warned_unknown.add(event)
            log.warning("flight: undeclared event %r dropped "
                        "(register it in grit_tpu.obs.flight.EVENTS)",
                        event)
        return
    family = event.split(".", 1)[0]
    FLIGHT_EVENTS.inc(phase=family)
    rec.write(event, event not in _NO_FSYNC, fields)
    profile.on_flight_event(rec, event)


def _resolve(dir_path: str | None) -> Recorder | None:
    """The recorder for an event: the log governing ``dir_path`` when
    given (keeping the configured recorder — and its role — when it IS
    that log), else the configured recorder."""
    cur = current()
    if dir_path is None:
        return cur
    near = _find_near(dir_path)
    if near is None:
        return cur
    if cur is not None and os.path.abspath(cur.path) == \
            os.path.abspath(near.path):
        return cur
    return near


def _dir_recorder() -> Recorder | None:
    tee_dir = str(config.FLIGHT_DIR.get())
    if not tee_dir:
        return None
    try:
        os.makedirs(tee_dir, exist_ok=True)
    except OSError:
        return None
    path = os.path.join(
        tee_dir, f"flight-{_HOST}-{os.getpid()}.jsonl")
    return Recorder(path, "manager", "manager")


def _find_near(dir_path: str) -> Recorder | None:
    d = os.path.abspath(dir_path)
    with _lock:
        if d in _near_cache:
            return _near_cache[d]
    probe = d
    found: str | None = None
    for _ in range(5):
        p = os.path.join(probe, FLIGHT_LOG_FILE)
        if os.path.isfile(p):
            found = p
            break
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    rec = (Recorder(found, _default_uid(os.path.dirname(found)), "device")
           if found is not None else None)
    with _lock:
        if len(_near_cache) >= 256:
            _near_cache.clear()
        _near_cache[d] = rec
    return rec


def read_flight_file(path: str) -> list[dict[str, Any]]:
    """Parse one flight JSONL log. A torn trailing line (crashed writer)
    is skipped, not fatal — the analyzer reconstructs the partial
    timeline and marks the gap."""
    out: list[dict[str, Any]] = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "ev" in rec:
                out.append(rec)
    return out
