"""Live migration progress: the telemetry plane's in-process tracker.

Everything observability before this module was post-hoc (the flight
recorder is analyzed after the migration) or edge-triggered (a counter
moves when an event fires): while a migration RUNS, nothing answered
"how many bytes crossed, how fast, and when will it finish?". The fleet
drain scheduler and multi-host streams (ROADMAP items 1/3) need exactly
that — bandwidth budgeting and wave rollback are decisions about
migrations in flight, not completed ones.

One :class:`ProgressTracker` per migration role in this process
("source" = checkpoint agent, "destination" = restore agent, "workload"
= the restored pod's place loop), fed from the byte accounting that
already exists on the data path:

- the HBM dump's streaming mirror (``_MirrorWriter``) and the wire
  sender count source bytes as they drain;
- the wire receiver and the staged transfer count destination bytes as
  frames/chunks land;
- the pre-copy convergence loop reports round number, dirty rate and
  link rate.

Three publication paths, none of which touch the data path's locks:

- **Prometheus gauges** (``grit_progress_*``) refreshed by the periodic
  sampler (:mod:`grit_tpu.obs.sampler`) so scrapes between events never
  read stale values;
- **the CRD status subresource**: the agent's heartbeat lease stamps
  :func:`annotation_value` as ``grit.dev/progress`` on its own Job in
  the SAME patch as the lease renewal (no new write amplification), and
  the manager controllers fold it into ``Checkpoint/Restore
  status.progress``;
- **a node-local snapshot file** (``.grit-progress.json``, atomically
  replaced next to the flight log) that ``gritscope watch`` tails for
  its live waterfall.

The tracker is pure bookkeeping (a lock around a few ints) — hot-path
feeders pay one dict hit and an integer add, and every publication is
pull-based on somebody else's cadence.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Iterable

from grit_tpu.metadata import PROGRESS_FILE
from grit_tpu.obs.metrics import (
    PROGRESS_BYTES_SHIPPED,
    PROGRESS_ETA_SECONDS,
    PROGRESS_RATE_BPS,
    PROGRESS_TOTAL_BYTES,
)

log = logging.getLogger(__name__)

#: Sliding window (seconds) the instantaneous rate/ETA derive from: long
#: enough to smooth frame bursts, short enough that a stall shows within
#: one watchdog poll.
RATE_WINDOW_S = 20.0

ROLE_SOURCE = "source"
ROLE_DESTINATION = "destination"
ROLE_WORKLOAD = "workload"


class ProgressTracker:
    """One migration leg's live counters. Thread-safe; bytes are
    monotonic by construction (a feeder can only add)."""

    def __init__(self, uid: str, role: str,
                 publish_dir: str | None = None,
                 ordinal: int | None = None,
                 clone: int | None = None) -> None:
        self.uid = uid
        self.role = role
        # Gang slice migration: this leg's host ordinal. Rides the
        # snapshot as "ord" (the per-host key the manager's
        # status.progress.hosts fan-in and gritscope watch group by);
        # the Prometheus role label stays the bounded base role — the
        # per-process gauges are per-host by construction anyway.
        self.ordinal = ordinal
        # RestoreSet fan-out: this restore leg's clone ordinal
        # (grit.dev/clone-ordinal → GRIT_CLONE_ORDINAL). Every clone
        # derives the SAME uid from the shared snapshot name, so the
        # ordinal is what lets `gritscope watch --restoreset` key live
        # per-clone progress files apart (PR 14's folded view was
        # deliberately source-only for exactly this ambiguity).
        self.clone = clone
        self._dir = publish_dir
        self._lock = threading.Lock()
        self._bytes = 0
        self._total = 0
        self._round = -1  # -1 = no pre-copy loop ran
        self._phase = ""
        self._dirty_bps: float | None = None
        self._link_bps: float | None = None
        # stream -> [bytes, first_mono, last_mono]: per-stream totals AND
        # active windows, so per-stream/channel throughput is derivable
        # (the N×N multi-host item budgets by exactly this).
        self._streams: dict[str, list[float]] = {}
        # Seeded with (t0, 0) so a leg that ships everything in one add
        # still has a baseline to rate against.
        self._samples: deque[tuple[float, int]] = deque(
            [(time.monotonic(), 0)])
        self._started_wall = time.time()
        self._advanced_wall = self._started_wall  # last FORWARD progress
        self._first_byte_mono: float | None = None
        self._last_byte_mono: float | None = None
        self._last_publish = 0.0
        # Most recent resource-ledger stamp (grit_tpu.obs.profile
        # sample_ledger: live cores/IO rates/python share) — ledger
        # updates are NOT forward progress, so they never touch
        # _advanced_wall (a stalled transfer with a healthy sampler must
        # still trip the watchdog's ProgressStalled verdict).
        self._ledger: dict[str, Any] | None = None
        # Standby arm state (grit_tpu.agent.standby): lastBaseAt /
        # backlogBytes / tickAt / round counters. Like the ledger,
        # stamping it is NOT forward progress (idle-armed is a
        # legitimate state) — only shipped rounds bump advancedAt, via
        # note_round/add_bytes on the normal feeders.
        self._standby: dict[str, Any] | None = None

    # -- feeders (hot path: one lock, integer math) ---------------------------

    def add_bytes(self, n: int, stream: str | None = None) -> None:
        if n <= 0:
            return
        now = time.monotonic()
        with self._lock:
            self._bytes += n
            self._advanced_wall = time.time()
            if self._first_byte_mono is None:
                self._first_byte_mono = now
            self._last_byte_mono = now
            if stream is not None:
                slot = self._streams.get(stream)
                if slot is None:
                    self._streams[stream] = [n, now, now]
                else:
                    slot[0] += n
                    slot[2] = now
            self._samples.append((now, self._bytes))
            cutoff = now - RATE_WINDOW_S
            while len(self._samples) > 2 and self._samples[0][0] < cutoff:
                self._samples.popleft()

    def set_total(self, nbytes: int) -> None:
        """Best current estimate of bytes to ship; grows monotonically
        (more containers / rounds discovered), never shrinks."""
        with self._lock:
            self._total = max(self._total, int(nbytes))

    def add_total(self, nbytes: int) -> None:
        """Accumulate into the total: for feeders that see the work in
        independent batches (the post-copy restore places a hot subset,
        then the cold tail — each leg knows only ITS arrays, and a
        max() of subset sums would let bytesShipped run past the
        total)."""
        if nbytes > 0:
            with self._lock:
                self._total += int(nbytes)

    def note_round(self, rnd: int) -> None:
        with self._lock:
            if rnd > self._round:
                self._round = rnd
                self._advanced_wall = time.time()

    def set_phase(self, phase: str) -> None:
        with self._lock:
            if phase != self._phase:
                self._phase = phase
                self._advanced_wall = time.time()

    def set_standby(self, **fields: object) -> None:
        """Merge standby arm-state fields (lastBaseAt, backlogBytes,
        tickAt, roundsShipped, ...) into the snapshot's ``standby``
        record. Deliberately never touches ``_advanced_wall``: the
        governor ticking while idle-armed is health, not progress."""
        with self._lock:
            if self._standby is None:
                self._standby = {}
            self._standby.update(fields)

    def standby_state(self) -> dict[str, Any] | None:
        with self._lock:
            return dict(self._standby) if self._standby is not None \
                else None

    def set_ledger(self, ledger: dict[str, Any]) -> None:
        """Stamp the per-process resource ledger (cpu cores, io rates,
        python share, codec saturation) onto this leg's snapshot."""
        with self._lock:
            self._ledger = dict(ledger)

    def set_rates(self, dirty_bps: float | None = None,
                  link_bps: float | None = None) -> None:
        with self._lock:
            if dirty_bps is not None:
                self._dirty_bps = float(dirty_bps)
            if link_bps is not None:
                self._link_bps = float(link_bps)

    # -- derived views ---------------------------------------------------------

    def rate_bps(self) -> float:
        """Windowed shipping rate: bytes over the recent sample window.
        0.0 while idle — a stalled leg decays to zero as the window
        slides past its last sample."""
        now = time.monotonic()
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            first_t, first_b = self._samples[0]
            last_t, last_b = self._samples[-1]
            if last_t < now - RATE_WINDOW_S:
                return 0.0  # stalled: the window slid past the last byte
            # Rate over now - first_t (not last_t - first_t): a leg that
            # went quiet mid-window reads as SLOWING, not as its last
            # burst's instantaneous speed.
            span = max(now - first_t, 1e-6)
            return max(0.0, (last_b - first_b) / span)

    def avg_rate_bps(self) -> float:
        """Whole-leg average: total bytes over the first→last byte wall.
        The number CI compares against the bench wire throughput."""
        with self._lock:
            if self._first_byte_mono is None \
                    or self._last_byte_mono is None:
                return 0.0
            span = self._last_byte_mono - self._first_byte_mono
            return self._bytes / span if span > 0 else 0.0

    def channel_rate_bps(self, prefix: str = "") -> float:
        """Average throughput of the streams whose name starts with
        ``prefix`` (e.g. ``"wire-"``): their summed bytes over the union
        first→last-byte window. 0.0 when no matching stream has a
        nonzero window. The number the CI lane checks against the bench
        wire throughput."""
        with self._lock:
            slots = [s for name, s in self._streams.items()
                     if name.startswith(prefix)]
            if not slots:
                return 0.0
            total = sum(s[0] for s in slots)
            span = max(s[2] for s in slots) - min(s[1] for s in slots)
            return total / span if span > 0 else 0.0

    def eta_s(self) -> float | None:
        """Seconds until the remaining bytes ship at the windowed rate;
        None while unknowable (no total yet, or zero rate with bytes
        still outstanding); 0.0 once shipped >= total."""
        with self._lock:
            total, shipped = self._total, self._bytes
        if total <= 0:
            return None
        if shipped >= total:
            return 0.0
        rate = self.rate_bps()
        if rate <= 0:
            return None
        return (total - shipped) / rate

    def snapshot(self) -> dict[str, Any]:
        """The publication record — the exact shape that lands in the
        ``grit.dev/progress`` Job annotation, ``status.progress`` on the
        CR, and the ``.grit-progress.json`` file."""
        eta = self.eta_s()
        rate = self.rate_bps()
        avg = self.avg_rate_bps()
        with self._lock:
            return {
                "uid": self.uid,
                "role": self.role,
                "phase": self._phase,
                "bytesShipped": self._bytes,
                "totalBytes": self._total,
                "round": self._round,
                "rateBps": round(rate, 1),
                "avgRateBps": round(avg, 1),
                "etaSeconds": (round(eta, 1) if eta is not None else None),
                "dirtyRateBps": (round(self._dirty_bps, 1)
                                 if self._dirty_bps is not None else None),
                "linkRateBps": (round(self._link_bps, 1)
                                if self._link_bps is not None else None),
                "streams": {
                    name: {"bytes": s[0],
                           "seconds": round(s[2] - s[1], 4)}
                    for name, s in self._streams.items()},
                "ledger": (dict(self._ledger)
                           if self._ledger is not None else None),
                # Only armed standbys carry the record — every other
                # migration's snapshot stays byte-identical to PR 8's.
                **({"standby": dict(self._standby)}
                   if self._standby is not None else {}),
                # Only slice legs carry the ordinal — single-host
                # snapshots stay byte-identical.
                **({"ord": self.ordinal}
                   if self.ordinal is not None else {}),
                # Only RestoreSet clone legs carry the clone ordinal.
                **({"clone": self.clone}
                   if self.clone is not None else {}),
                "startedAt": round(self._started_wall, 3),
                "advancedAt": round(self._advanced_wall, 3),
                "updatedAt": round(time.time(), 3),
            }

    # -- publications ----------------------------------------------------------

    def export_gauges(self) -> None:
        snap = self.snapshot()
        PROGRESS_BYTES_SHIPPED.set(snap["bytesShipped"], role=self.role)
        PROGRESS_TOTAL_BYTES.set(snap["totalBytes"], role=self.role)
        PROGRESS_RATE_BPS.set(snap["rateBps"], role=self.role)
        PROGRESS_ETA_SECONDS.set(
            snap["etaSeconds"] if snap["etaSeconds"] is not None else -1.0,
            role=self.role)

    def publish(self, min_interval_s: float = 0.0) -> bool:
        """Atomically replace the node-local snapshot file (the
        ``gritscope watch`` feed). Throttled by ``min_interval_s`` so
        callers on hot paths cannot turn it into per-chunk fsync
        traffic. Never raises — observability must not take down the
        data path."""
        if self._dir is None:
            return False
        now = time.monotonic()
        with self._lock:
            if min_interval_s and now - self._last_publish < min_interval_s:
                return False
            self._last_publish = now
        path = os.path.join(self._dir, PROGRESS_FILE)
        # Per-thread tmp: the lease beat thread, the sampler thread and
        # driver publish() calls can all run concurrently in one
        # process — a shared per-pid tmp would let two writers
        # interleave JSON and atomically install the torn result.
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            os.makedirs(self._dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(self.snapshot(), f)
            os.replace(tmp, path)
            return True
        except OSError as exc:
            log.warning("progress snapshot %s unwritable: %s", path, exc)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False


# -- process-global registry (one tracker per role) ---------------------------

_lock = threading.Lock()
_trackers: dict[str, ProgressTracker] = {}


def configure(uid: str, role: str,
              publish_dir: str | None = None,
              ordinal: int | None = None,
              clone: int | None = None) -> ProgressTracker:
    """Install a fresh tracker for ``role`` (a new migration leg starts
    from zero — the previous leg's counters must not leak into its
    rate window)."""
    tracker = ProgressTracker(uid, role, publish_dir=publish_dir,
                              ordinal=ordinal, clone=clone)
    with _lock:
        _trackers[role] = tracker
    return tracker


def uid_from_dir(dir_path: str) -> str:
    """The migration uid both ends derive independently: the checkpoint
    name is the work/stage dir basename (same convention as the flight
    recorder)."""
    return os.path.basename(os.path.normpath(dir_path)) or "migration"


def adopt(uid: str, role: str,
          publish_dir: str | None = None,
          ordinal: int | None = None,
          clone: int | None = None) -> ProgressTracker:
    """Keep the live tracker when it already belongs to this migration
    (a driver continuing a leg another driver started — run_checkpoint
    after a split-phase run_precopy_phase must not zero the counters);
    configure fresh otherwise."""
    with _lock:
        tracker = _trackers.get(role)
        if tracker is not None and tracker.uid == uid:
            if publish_dir and tracker._dir is None:
                tracker._dir = publish_dir
            if ordinal is not None and tracker.ordinal is None:
                tracker.ordinal = ordinal
            if clone is not None and tracker.clone is None:
                tracker.clone = clone
            return tracker
    return configure(uid, role, publish_dir=publish_dir, ordinal=ordinal,
                     clone=clone)


def ensure(role: str, uid: str = "",
           publish_dir: str | None = None) -> ProgressTracker:
    """The tracker for ``role``, creating one on first use (the
    workload's place loop has no driver that calls configure). A
    DIFFERENT non-empty uid replaces the tracker: a second migration in
    the same process must not inherit the first one's counters."""
    with _lock:
        tracker = _trackers.get(role)
        if tracker is None or (uid and tracker.uid != uid):
            tracker = ProgressTracker(uid, role, publish_dir=publish_dir)
            _trackers[role] = tracker
        return tracker


def get(role: str) -> ProgressTracker | None:
    with _lock:
        return _trackers.get(role)


def trackers() -> list[ProgressTracker]:
    with _lock:
        return list(_trackers.values())


def reset() -> None:
    """Forget every tracker (tests)."""
    with _lock:
        _trackers.clear()


def add_bytes(role: str, n: int, stream: str | None = None) -> None:
    """Feeder funnel: count ``n`` shipped bytes on ``role``'s tracker —
    one dict hit + int add when configured, a no-op when not."""
    tracker = get(role)
    if tracker is not None:
        tracker.add_bytes(n, stream=stream)


def wire_channel_totals(snapshot: object) -> dict[str, Any] | None:
    """Aggregate one SOURCE-leg snapshot's per-stream ``wire-k``
    channels into a single bandwidth line ``{bytes, seconds, streams,
    rateBps}`` (its ``GRIT_WIRE_STREAMS`` sockets are one src→dst
    session). None when the snapshot is not a source leg or shipped
    nothing over the wire — the shared kernel of the slice N×N
    ``hostPairs`` view and the single-host ``nodePairs`` line the
    fleet budgeter reads off every member migration."""
    if not isinstance(snapshot, dict):
        return None
    if snapshot.get("role") != ROLE_SOURCE:
        return None
    streams = snapshot.get("streams") or {}
    wire = {k: v for k, v in streams.items()
            if str(k).startswith("wire-") and isinstance(v, dict)}
    if not wire:
        return None
    total = sum(int(v.get("bytes", 0) or 0) for v in wire.values())
    secs = max((float(v.get("seconds", 0.0) or 0.0)
                for v in wire.values()), default=0.0)
    return {
        "bytes": total,
        "seconds": secs,
        "streams": len(wire),
        "rateBps": round(total / secs, 1) if secs > 0 else 0.0,
    }


def host_pair_channels(snapshots: Iterable[object],
                       mapping: dict[int, int] | None = None,
                       ) -> dict[str, dict[str, Any]]:
    """Aggregate slice-leg snapshots' per-stream ``wire-k`` channels
    into per-host-pair bandwidth lines — the N×N budgeting view the
    fleet scheduler consumes (one pair per source→destination host
    session; its ``GRIT_WIRE_STREAMS`` sockets sum into one line).

    ``mapping`` is the gang's source→destination ordinal relabeling
    (identity when None — the common case). Returns
    ``{"h0001->h0001": {bytes, seconds, streams, rateBps}}``; snapshots
    without an ``ord`` field (single-host legs) contribute nothing —
    their ``src->dst`` line is the NODE-pair one the controller derives
    via :func:`wire_channel_totals` (it, not the snapshot, knows the
    nodes)."""
    pairs: dict[str, dict[str, Any]] = {}
    for snap in snapshots:
        if not isinstance(snap, dict) or snap.get("ord") is None:
            continue
        totals = wire_channel_totals(snap)
        if totals is None:
            continue
        try:
            src = int(snap["ord"])
        except (TypeError, ValueError):
            continue
        dst = (mapping or {}).get(src, src)
        key = f"h{src:04d}->h{dst:04d}"
        rec = pairs.setdefault(
            key, {"bytes": 0, "seconds": 0.0, "streams": 0})
        rec["bytes"] += totals["bytes"]
        rec["seconds"] = max(rec["seconds"], totals["seconds"])
        rec["streams"] += totals["streams"]
    for rec in pairs.values():
        rec["rateBps"] = (round(rec["bytes"] / rec["seconds"], 1)
                          if rec["seconds"] > 0 else 0.0)
    return pairs


def annotation_value(role: str) -> str | None:
    """The JSON the heartbeat lease stamps as ``grit.dev/progress`` on
    the agent Job (compact separators: annotation bytes ride every lease
    patch)."""
    tracker = get(role)
    if tracker is None:
        return None
    return json.dumps(tracker.snapshot(), separators=(",", ":"))


def sample() -> None:
    """One sampler tick: refresh the progress gauges and the node-local
    snapshot files for every live tracker."""
    for tracker in trackers():
        tracker.export_gauges()
        tracker.publish(min_interval_s=0.5)


def read_progress_file(path: str) -> dict[str, Any] | None:
    """Parse one ``.grit-progress.json`` snapshot; None on a torn or
    missing file (the writer replaces it atomically, but a reader can
    still race a crashed writer's leftover tmp)."""
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None
