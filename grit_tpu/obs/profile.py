"""Sampling CPU profiler — the pprof analogue for the Python processes.

Parity: reference mounts net/http/pprof on the manager metrics mux behind
``--enable-profiling`` (``pkg/util/profile/profile.go:12-24``,
``cmd/grit-manager/app/manager.go:88-92``). Python has no in-process pprof;
this is a dependency-free wall-clock sampler over ``sys._current_frames``
emitting collapsed-stack format (one ``count stack;frames`` line per unique
stack — directly flamegraph.pl / speedscope compatible).
"""

from __future__ import annotations

import sys
import threading
import time

MAX_SECONDS = 30.0


def _format_stack(frame) -> str:
    parts: list[str] = []
    f = frame
    while f is not None:
        code = f.f_code
        parts.append(
            f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})"
        )
        f = f.f_back
    return ";".join(reversed(parts))


def sample_profile(seconds: float = 5.0, hz: float = 100.0) -> str:
    """Sample all threads for ``seconds`` at ``hz``; returns collapsed
    stacks sorted by sample count (descending)."""
    seconds = min(max(seconds, 0.1), MAX_SECONDS)
    me = threading.get_ident()
    counts: dict[str, int] = {}
    total = 0
    deadline = time.monotonic() + seconds
    interval = 1.0 / hz
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            key = _format_stack(frame)
            counts[key] = counts.get(key, 0) + 1
            total += 1
        time.sleep(interval)
    lines = [
        f"{n} {stack}"
        for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])
    ]
    header = (
        f"# wall-clock samples: {total} over {seconds:.1f}s at {hz:.0f} Hz "
        f"({len(counts)} unique stacks)\n"
    )
    return header + "\n".join(lines) + ("\n" if lines else "")
