"""Phase-scoped sampling profiler + per-process resource ledger.

ROADMAP item 5 claims "the Python frame loop is now the bottleneck"
behind the ~20x gap between device read and wire throughput — but until
this module nothing in the tree could prove it: the flight recorder
attributes *wall clock* to phases, never *CPU/IO within a phase*. This
is the instrument that produces the PhoenixOS-style per-stage cost
breakdown for every migration, automatically:

- **PhaseProfiler** — armed by the flight recorder's phase brackets
  (every ``*.start``/``*.end`` pair the :data:`PROFILED_PHASES` table
  names). While any bracket is open, a sampling thread walks
  ``sys._current_frames()`` at ``GRIT_PROF_HZ`` and classifies each
  thread sample as on-CPU **python**, **native** (GIL-released C
  extension — codec, gritio: the Python frame is frozen while CPU still
  burns), **syscall** wait, **lock** wait (futex — includes GIL
  contention), **idle**, or **unknown** (no /proc and no frame hint).
  Classification combines frame inspection with per-thread
  ``/proc/self/task/<tid>/stat`` utime/stime deltas and ``wchan``.
  When the bracket closes, the phase's collapsed stacks land next to
  the flight log as ``.grit-prof-<phase>.folded`` (flamegraph.pl /
  speedscope compatible; category is the first stack segment), teed
  into ``GRIT_FLIGHT_DIR`` for CI artifact collection, and — like the
  flight log — excluded from every transfer tree walk.

- **Resource ledger** — sampled on the existing observability-sampler
  cadence (:mod:`grit_tpu.obs.sampler`): process CPU seconds,
  ``/proc/self/io`` read/write bytes, RSS, context switches and codec-
  pool saturation, published as ``grit_prof_*`` gauges and stamped (as
  windowed rates) into every live progress tracker's snapshot so
  ``gritscope watch`` can show "wire-send: 0.9 cores, 92% python" live.

- **``sample_profile``** — the debug-server endpoint
  (``/debug/pprof/profile``), now routed through the same sampling/
  classification engine (one implementation for both paths), with the
  unique-stack cardinality cap (``GRIT_PROF_MAX_STACKS`` + one
  ``[overflow]`` bucket) and the handler's own thread excluded.

The profiler only ever arms on flight events, so with ``GRIT_FLIGHT``
off (the production default) it costs one dict miss per flight emit —
nothing samples. ``GRIT_PROF_HZ=0`` disables sampling even when flight
recording is on.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from types import FrameType
from typing import TYPE_CHECKING, Any

from grit_tpu.api import config

if TYPE_CHECKING:
    from grit_tpu.obs.flight import Recorder
from grit_tpu.metadata import PROF_FILE_PREFIX
from grit_tpu.obs.metrics import (
    PROF_CODEC_POOL_SATURATION,
    PROF_CPU_SECONDS,
    PROF_CTX_SWITCHES,
    PROF_IO_BYTES,
    PROF_RSS_BYTES,
    PROF_SAMPLE_TICKS,
    PROF_TICK_SECONDS,
)

log = logging.getLogger(__name__)

#: Closed classification vocabulary (the bounded label set of
#: ``grit_prof_sample_ticks_total`` and the folded-header categories).
CATEGORIES = ("python", "native", "syscall", "lock", "idle", "unknown")

#: The cap's overflow bucket: stacks beyond ``GRIT_PROF_MAX_STACKS``
#: fold here instead of growing the table.
OVERFLOW_STACK = "[overflow]"

MAX_SECONDS = 30.0  # debug-endpoint ceiling (unchanged contract)

#: Flight phase brackets that arm/disarm the profiler, keyed by the
#: gritscope phase name the folded file is labeled with. Event names are
#: literals from ``grit_tpu.obs.flight.EVENTS`` (this table is a
#: *consumer* of the registry, like gritscope's phase model).
PROFILED_PHASES = {
    "quiesce": ("quiesce.start", "quiesce.end"),
    "dump": ("dump.start", "dump.end"),
    "precopy_round": ("precopy.round.start", "precopy.round.end"),
    "criu_dump": ("criu.dump.start", "criu.dump.end"),
    "upload": ("upload.start", "upload.end"),
    "wire_send": ("wire.send.start", "wire.send.end"),
    "wire_commit": ("wire.commit.start", "wire.commit.end"),
    "wire_recv": ("wire.recv.open", "wire.recv.commit"),
    "stage": ("stage.start", "stage.end"),
    "criu_restore": ("criu.restore.start", "criu.restore.end"),
    "place": ("place.start", "place.end"),
    "postcopy_tail": ("postcopy.tail.start", "postcopy.tail.end"),
    "resume": ("resume.start", "resume.end"),
}

_ARM_EVENTS = {start: phase
               for phase, (start, _end) in PROFILED_PHASES.items()}
_DISARM_EVENTS = {end: phase
                  for phase, (_start, end) in PROFILED_PHASES.items()}
# The receive window also closes on failure — a poisoned wire session's
# profile is exactly the one worth reading.
_DISARM_EVENTS["wire.recv.fail"] = "wire_recv"


# -- sample classification ----------------------------------------------------

# Stdlib files whose presence at the TOP of a sampled stack identifies
# the wait class even without /proc (Event.wait/Condition.wait/Queue.get
# have pure-Python frames; socket/selectors wrap their blocking
# builtins in Python helpers).
_LOCK_FILES = ("threading.py", "queue.py")
_SYSCALL_FILES = ("socket.py", "socketserver.py", "selectors.py",
                  "ssl.py", "subprocess.py")
# Call sites that are thin wrappers around GIL-releasing C work: a top
# frame from one of these burning CPU is native compute even on the
# first sample (before the frozen-frame signal exists).
_NATIVE_FUNCS = frozenset({
    "compress", "decompress", "flush", "crc32", "digest", "hexdigest",
})
# The ctypes FFI funnels of the native data planes: a thread whose TOP
# frame sits inside one of these modules while CPU burns is EXECUTING
# the C call behind it (ctypes releases the GIL; C callables push no
# Python frame, so the wrapper function stays the sampled leaf). The
# frozen-frame signal alone misses them — each chunk is a NEW wrapper
# frame, so a per-chunk loop over long GIL-released calls reads as
# "moving frames = python" without this hint.
_NATIVE_FFI_FILES = ("grit_tpu/native/file.py",
                     "grit_tpu/native/__init__.py",
                     "grit_tpu/native/wire.py")


# (id(code), f_lasti) -> rendered frame label. f_lineno decoding and
# string formatting are the sampler's per-tick hot cost (GIL-held,
# stolen from the data path being measured); a frame at the same
# instruction offset renders identically, and most sampled frames are
# parents frozen at a call site. Bounded; cleared on overflow.
_label_cache: dict[tuple[int, int], str] = {}


def _frame_label(f: FrameType) -> str:
    key = (id(f.f_code), f.f_lasti)
    label = _label_cache.get(key)
    if label is None:
        code = f.f_code
        label = (f"{code.co_name} "
                 f"({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
        if len(_label_cache) >= 8192:
            _label_cache.clear()
        _label_cache[key] = label
    return label


def _format_stack(frame: FrameType) -> str:
    parts: list[str] = []
    f: FrameType | None = frame
    while f is not None:
        parts.append(_frame_label(f))
        f = f.f_back
    return ";".join(reversed(parts))


def _read_small(path: str) -> bytes | None:
    """One-shot os.open/os.read/os.close of a small proc file: every
    syscall return re-acquires the GIL (a full scheduler round trip
    behind busy threads), so the read path is three syscalls, not
    open()'s buffered-IO half dozen."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return None
    try:
        return os.read(fd, 1024)
    except OSError:
        return None
    finally:
        os.close(fd)


def _task_stat(tid: int) -> tuple[str, int] | None:
    """(state, utime+stime clock ticks) for one OS thread, or None when
    /proc is unreadable (non-Linux, masked sandbox, exited thread)."""
    data = _read_small(f"/proc/self/task/{tid}/stat")
    if data is None:
        return None
    try:
        # comm (field 2) may contain spaces/parens: split after the
        # LAST ')' — state is field 3, utime/stime fields 14/15.
        rest = data.rsplit(b")", 1)[1].split()
        return rest[0].decode("ascii", "replace"), \
            int(rest[11]) + int(rest[12])
    except (IndexError, ValueError):
        return None


def _task_wchan(tid: int) -> str:
    """The kernel function the thread is blocked in ("" / "0" when
    running or when the kernel masks wchan)."""
    data = _read_small(f"/proc/self/task/{tid}/wchan")
    if data is None:
        return ""
    return data.decode("ascii", "replace").strip()


#: CPU-rate floor (fraction of a core over the sweep window) above
#: which a thread counts as on-CPU. Tick-based kernels bill a whole
#: jiffy to whichever thread the accounting tick catches, and timer-
#: sleep EXPIRIES are correlated with those ticks — a 20 Hz sleeper
#: measures up to ~0.25 cores of phantom CPU. Real compute measures
#: 0.4+ even on a saturated 2-core host, so the floor sits between.
#: Genuinely-computing-but-starved threads below it still classify
#: python via the moving-frame fallback; only a starved *frozen-frame*
#: native worker can undercount, and it is mostly waiting then anyway.
ON_CPU_RATE = 0.3


def classify_sample(frame: FrameType, state: str,
                    cpu_rate: float | None,
                    frozen: bool, wchan: str) -> str:
    """One thread sample -> a :data:`CATEGORIES` member. ``cpu_rate``
    is the thread's CPU seconds per wall second over the last sweep
    window (None before a baseline exists).

    Order is load-bearing: CPU-burn evidence first (a busy Python
    thread's instantaneous wchan is often futex — it is waiting for the
    GIL *we* hold while sampling — and must not read as lock-wait),
    then kernel truth (state/wchan), then frame hints, then idle.
    """
    top = frame.f_code
    top_file = top.co_filename.rsplit("/", 1)[-1]
    if state == "S" and wchan and ("nanosleep" in wchan
                                   or "hrtimer" in wchan):
        # Asleep on a timer, by choice. Outranks the billed CPU rate:
        # tick-based kernels bill a whole jiffy to whichever thread the
        # accounting tick catches, and sleep EXPIRIES are correlated
        # with those ticks — a 20 Hz sleeper can read 0.2 cores of
        # phantom CPU. A timer sleep is never a GIL wait (those are
        # futex), so this cannot eat real compute samples.
        return "idle"
    # R-state alone only counts before a rate baseline exists: on a
    # contended host every starved thread is runnable-waiting much of
    # the time — the measured rate, once available, is the truth.
    on_cpu = (cpu_rate > ON_CPU_RATE) if cpu_rate is not None \
        else state == "R"
    if on_cpu:
        # Burning CPU (or runnable right now). A frozen Python frame
        # (identical frame/instruction across ticks) while CPU burns
        # means the GIL is released — a C extension is doing the work.
        if frozen or top.co_name in _NATIVE_FUNCS \
                or top.co_filename.endswith(_NATIVE_FFI_FILES):
            return "native"
        return "python"
    if state == "D":
        return "syscall"  # uninterruptible: disk/device wait
    if wchan and wchan != "0":
        if "futex" in wchan:
            return "lock"
        if "nanosleep" in wchan or "hrtimer" in wchan:
            return "idle"
        if any(k in wchan for k in (
                "poll", "select", "epoll", "sock", "skb", "pipe",
                "unix_stream", "io_schedule", "wait_on", "fsync",
                "sync", "flock", "lock_page", "read", "write", "accept")):
            if ("poll" in wchan or "select" in wchan) \
                    and top_file not in _SYSCALL_FILES:
                # CPython <= 3.10 implements time.sleep via select():
                # the sleeper parks in poll_schedule_timeout, kernel-
                # indistinguishable from an fd poll. A poll/select wait
                # whose sampled Python leaf is NOT an I/O module is a
                # timer sleep, not I/O.
                return "idle"
            return "syscall"
    if top_file in _LOCK_FILES:
        return "lock"
    if top_file in _SYSCALL_FILES:
        return "syscall"
    if not frozen:
        # The Python frame MOVED since the last tick: the thread
        # executed Python in between, whatever the (sticky, possibly
        # pre-baseline) kernel info says — a GIL-waiting busy thread
        # reads S-state at the sweep but is still the frame loop.
        return "python"
    if state:
        return "idle"
    return "unknown"


# -- per-phase aggregation ----------------------------------------------------


class PhaseAgg:
    """One phase bracket's sample table: (category, stack) -> count,
    with the unique-stack cardinality cap."""

    __slots__ = ("phase", "out_dir", "uid", "role", "hz", "max_stacks",
                 "counts", "cats", "ticks", "overflow", "started_mono",
                 "seconds")

    def __init__(self, phase: str, out_dir: str | None, uid: str,
                 role: str, hz: float, max_stacks: int) -> None:
        self.phase = phase
        self.out_dir = out_dir
        self.uid = uid
        self.role = role
        self.hz = hz
        self.max_stacks = max(1, int(max_stacks))
        self.counts: dict[tuple[str, str], int] = {}
        self.cats: dict[str, int] = {}
        self.ticks = 0
        self.overflow = 0
        self.started_mono = time.monotonic()
        # Wall seconds the bracket(s) actually covered, stamped at
        # disarm. Share math uses ticks (achieved rate), never the
        # nominal hz: a starved sampler under-ticks, it does not lie.
        self.seconds = 0.0

    def add(self, category: str, stack: str, n: int = 1) -> None:
        self.cats[category] = self.cats.get(category, 0) + n
        key = (category, stack)
        if key not in self.counts and len(self.counts) >= self.max_stacks:
            self.overflow += n
            key = (category, OVERFLOW_STACK)
        self.counts[key] = self.counts.get(key, 0) + n

    def snapshot(self) -> "PhaseAgg":
        """Detached copy for writing/merging. ``dict()`` of a dict is a
        single C-level copy — atomic under the GIL — so this is safe
        against a sampler thread still holding a reference to this agg
        mid-tick (a Python-level iteration over the live dicts is not:
        it raises ``dictionary changed size during iteration``)."""
        out = PhaseAgg(self.phase, self.out_dir, self.uid, self.role,
                       self.hz, self.max_stacks)
        out.counts = dict(self.counts)
        out.cats = dict(self.cats)
        out.ticks = self.ticks
        out.overflow = self.overflow
        out.started_mono = self.started_mono
        out.seconds = self.seconds
        return out

    def merge(self, other: "PhaseAgg") -> None:
        """Fold ``other`` in (a re-armed phase — pre-copy rounds —
        accumulates into one folded file per phase and dir). Pass a
        :meth:`snapshot` when ``other`` may still be receiving
        samples."""
        self.ticks += other.ticks
        self.seconds += other.seconds
        self.overflow += other.overflow
        for cat, n in other.cats.items():
            self.cats[cat] = self.cats.get(cat, 0) + n
        for (cat, stack), n in other.counts.items():
            key = (cat, stack)
            if stack != OVERFLOW_STACK and key not in self.counts \
                    and len(self.counts) >= self.max_stacks:
                # Newly lost identity in the merge — count it. The
                # incoming [overflow] buckets themselves are already in
                # other.overflow (added above); re-counting them here
                # would double-bill depending on dict order.
                key = (cat, OVERFLOW_STACK)
                self.overflow += n
            self.counts[key] = self.counts.get(key, 0) + n

    def samples(self) -> int:
        return sum(self.cats.values())

    def header(self) -> dict[str, Any]:
        return {
            "phase": self.phase,
            "uid": self.uid,
            "role": self.role,
            "hz": self.hz,
            "ticks": self.ticks,
            "seconds": round(self.seconds, 4),
            "samples": self.samples(),
            "categories": dict(sorted(self.cats.items())),
            "overflow": self.overflow,
        }

    def folded(self) -> str:
        """Collapsed-stack text: a ``# grit-prof <json>`` header line,
        then ``category;frame;frame count`` lines, hottest first."""
        lines = ["# grit-prof " + json.dumps(self.header(),
                                             sort_keys=True)]
        for (cat, stack), n in sorted(self.counts.items(),
                                      key=lambda kv: -kv[1]):
            lines.append(f"{cat};{stack} {n}")
        return "\n".join(lines) + "\n"


def prof_file_name(phase: str) -> str:
    """Per-phase, per-PROCESS artifact name. The pid suffix is load-
    bearing: the agent (device/hook.py) and the workload process
    (device/snapshot.py, via emit_near) both bracket the dump phase
    against the same governing flight-log dir, and a shared name would
    let the agent's mostly-idle enclosing bracket os.replace away the
    workload's compute samples. gritscope profile merges per phase
    across files, so N processes just mean N inputs."""
    return f"{PROF_FILE_PREFIX}{phase}-p{os.getpid()}.folded"


# The folded artifact's READER lives in tools/gritscope/profilecmd.py
# (read_folded): gritscope must stay importable without the grit_tpu
# tree, so the parser belongs with the analyzer — one reader, no
# drift-prone twin here. Tests and bench consume the artifacts through
# it.


# -- the profiler -------------------------------------------------------------


class PhaseProfiler:
    """Continuous all-thread sampler, active only while at least one
    phase bracket is armed. One instance per process (see
    :func:`default_profiler`); ``sample_once`` is synchronous and
    lock-ordered so tests can drive it without the thread."""

    #: Sliding window (seconds) the ledger's live python-share derives
    #: from (matches the progress tracker's rate window).
    SHARE_WINDOW_S = 20.0

    #: Kernel-info (/proc stat+wchan) sweep cadence floor. Per-thread
    #: /proc reads are syscalls, and every syscall return must
    #: re-acquire the GIL AND a CPU — on a saturated host a single read
    #: measured >100 ms, which at per-tick granularity turned a 50 Hz
    #: profiler into a 3 Hz one. CPU-time granularity is a 10 ms jiffy
    #: anyway, so the sweep runs at most at ~10 Hz with sticky
    #: per-thread kernel info, and the per-tick cost stays one
    #: ``sys._current_frames`` call (zero syscalls).
    PROC_SWEEP_S = 0.1

    #: Overhead bound on the sweep itself: each sweep's measured wall
    #: cost pushes the next sweep out to ``cost / SWEEP_DUTY`` — a
    #: starved sweep self-decimates instead of eating the blackout
    #: window it is measuring (fidelity degrades, overhead stays <3%;
    #: together with TICK_DUTY this keeps the whole profiler under the
    #: bench's 5% overhead ceiling by construction).
    SWEEP_DUTY = 0.03

    #: Until every sampled thread has a CPU-rate baseline (two stat
    #: readings), sweeps re-run on this spacing regardless of the duty
    #: bound: a thread caught momentarily runnable at the FIRST sweep
    #: must not stay classified on-CPU for the whole adaptive gap. Long
    #: enough that 1-2 wakeup jiffies over the gap stay under
    #: :data:`ON_CPU_RATE`.
    BASELINE_SWEEP_S = 0.4

    #: Duty bound on the TICK itself (frames + classification +
    #: formatting, GIL-held — stolen from exactly the data path being
    #: measured): the loop stretches its interval so ticking costs at
    #: most this fraction of wall clock. At the default rate a cheap
    #: tick keeps the nominal cadence; a many-threaded process
    #: self-decimates instead of taxing the blackout window (share math
    #: uses achieved ticks, so fidelity degrades, truth does not).
    TICK_DUTY = 0.02

    def __init__(self, hz: float | None = None,
                 max_stacks: int | None = None) -> None:
        self._hz_override = hz
        self._max_override = max_stacks
        self._lock = threading.Lock()
        self._armed: dict[str, PhaseAgg] = {}
        self._arm_depth: dict[str, int] = {}
        # (out_dir, phase, uid) -> PhaseAgg accumulated across re-arms,
        # so a phase that brackets repeatedly (pre-copy rounds) keeps
        # ONE stable folded file with cumulative counts. uid is part of
        # the key: a later migration reusing the same work dir must not
        # merge into (or inherit the header uid of) the previous one.
        self._history: dict[tuple[str, str, str], PhaseAgg] = {}
        self._exclude: set[int] = set()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # ident -> frame_marker from the previous tick (frozen-frame
        # detection: identical marker while CPU burns = GIL released)
        self._frame_state: dict[int, tuple] = {}
        # ident -> (state, cpu_rate, wchan) from the last /proc sweep
        self._kinfo: dict[int, tuple] = {}
        # ident -> (cumulative cpu ticks, reading time): the rate
        # baseline, rolled forward only when the pair spacing is wide
        # enough for jiffy-resolution rates.
        self._cpu_prev: dict[int, tuple] = {}
        self._next_sweep = 0.0
        self._last_tick_cost = 0.0
        # recent per-tick category counts for the live ledger share
        self._recent: deque[tuple[float, dict[str, int]]] = deque()

    # -- knobs (read live: tests and Jobs flip env) ---------------------------

    def hz(self) -> float:
        if self._hz_override is not None:
            return float(self._hz_override)
        return float(config.PROF_HZ.get())

    def max_stacks(self) -> int:
        if self._max_override is not None:
            return int(self._max_override)
        return int(config.PROF_MAX_STACKS.get())

    def enabled(self) -> bool:
        return self.hz() > 0

    # -- arm / disarm ---------------------------------------------------------

    def arm(self, phase: str, out_dir: str | None, uid: str = "",
            role: str = "") -> None:
        if not self.enabled():
            return
        with self._lock:
            depth = self._arm_depth.get(phase, 0)
            self._arm_depth[phase] = depth + 1
            if depth == 0:
                if not self._armed:
                    # Fresh arming epoch: the duty bound caps a LIVE
                    # loop's overhead, but one expensive final tick of
                    # the previous epoch (GIL starvation on a saturated
                    # box) otherwise carries a 50x-stretched interval
                    # into this epoch's first wait — a back-to-back
                    # in-process migration (the obs lane's native-vs-
                    # python compare baseline) then closes every phase
                    # with zero ticks.
                    self._last_tick_cost = 0.0
                self._armed[phase] = PhaseAgg(
                    phase, out_dir, uid, role, self.hz(),
                    self.max_stacks())
            self._ensure_thread_locked()

    def disarm(self, phase: str) -> None:
        with self._lock:
            depth = self._arm_depth.get(phase, 0)
            if depth <= 0:
                return
            self._arm_depth[phase] = depth - 1
            if depth > 1:
                return
            self._arm_depth.pop(phase, None)
            agg = self._armed.pop(phase, None)
            if agg is None:
                return
            agg.seconds = time.monotonic() - agg.started_mono
            # A sampler tick in flight captured the armed list BEFORE
            # this pop and may still be adding samples: merge/write
            # from a detached snapshot, never the live object.
            snap = agg.snapshot()
            key = (agg.out_dir or "", phase, agg.uid)
            merged = self._history.get(key)
            if merged is None:
                # Bounded: evict oldest entries (insertion order), not
                # the whole table — a clear() mid-pre-copy would drop
                # the earlier rounds from the cumulative artifact.
                while len(self._history) >= 128:
                    self._history.pop(next(iter(self._history)))
                self._history[key] = snap
                merged = snap
            else:
                merged.merge(snap)
            out = merged.snapshot()
        self._write(out)

    def armed_phases(self) -> list[str]:
        with self._lock:
            return sorted(self._armed)

    def exclude_thread(self, ident: int) -> None:
        with self._lock:
            self._exclude.add(ident)

    # -- sampling -------------------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="grit-prof-sampler", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            hz = self.hz()
            base = 1.0 / hz if hz > 0 else 0.5
            start = time.monotonic()
            while True:
                # Waits are sliced so the duty stretch is re-read each
                # slice: a duty-stretched interval can reach tens of
                # seconds, and an unsliced wait would (a) park the
                # thread alive-but-useless long past every disarm and
                # (b) sleep straight through a fresh arming epoch's
                # duty reset — the re-armed migration would then close
                # every phase with zero ticks.
                interval = max(base,
                               self._last_tick_cost / self.TICK_DUTY)
                remaining = start + interval - time.monotonic()
                if remaining <= 0:
                    break
                if self._stop.wait(min(remaining, 0.25)):
                    return
                with self._lock:
                    if not self._armed:
                        # Last phase disarmed: the thread exits instead
                        # of idling in every process forever; the next
                        # arm starts a fresh one.
                        self._thread = None
                        return
            with self._lock:
                if not self._armed:
                    self._thread = None
                    return
            try:
                self.sample_once()
            except Exception as exc:  # noqa: BLE001 — never kill sampling
                log.warning("profiler tick failed: %s", exc)

    def stop(self) -> None:
        """Halt the sampling thread (tests / reset); armed state stays."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None

    def _proc_sweep(self, idents: list[int]) -> None:
        """Refresh sticky kernel info (state, cpu rate, wchan) for the
        given threads. Syscall-heavy — run on the adaptive cadence, not
        per tick."""
        natives = {t.ident: getattr(t, "native_id", None)
                   for t in threading.enumerate()}
        now = time.monotonic()
        min_gap = self.BASELINE_SWEEP_S * 0.8
        try:
            jiffy = 1.0 / (os.sysconf("SC_CLK_TCK") or 100)
        except (OSError, ValueError, AttributeError):
            jiffy = 0.01
        for ident in idents:
            nid = natives.get(ident)
            stat = _task_stat(nid) if nid else None
            if stat is None:
                self._kinfo[ident] = ("", None, "")
                self._cpu_prev.pop(ident, None)
                continue
            state, cpu = stat
            prev = self._cpu_prev.get(ident)
            # CPU seconds per wall second over the baseline window: a
            # sleeper's single wakeup jiffy over a long gap must not
            # read as compute, so the RATE (not the raw delta) is what
            # classification thresholds. The baseline pair only rolls
            # forward on adequately-spaced readings (a short-gap rate
            # would let one jiffy clear the threshold); in between, the
            # previous rate is carried.
            if prev is None:
                self._cpu_prev[ident] = (cpu, now)
                cpu_rate = None
            elif now - prev[1] >= min_gap:
                cpu_rate = (cpu - prev[0]) * jiffy / (now - prev[1])
                self._cpu_prev[ident] = (cpu, now)
            else:
                cpu_rate = self._kinfo.get(ident, ("", None, ""))[1]
            # wchan only where it can change the verdict (S-state):
            # R/D threads classify without it. Read it regardless of
            # the billed rate — the sleep-wchan override in
            # classify_sample needs it exactly when phantom CPU billing
            # makes the rate lie.
            wchan = ""
            if state == "S" and nid:
                wchan = _task_wchan(nid)
            self._kinfo[ident] = (state, cpu_rate, wchan)
        for known in (self._kinfo, self._cpu_prev, self._frame_state):
            for ident in list(known):
                if ident not in natives:
                    del known[ident]

    def sample_once(self) -> dict[str, int]:
        """One tick: sample + classify every thread, credit every armed
        phase. Returns this tick's per-category sample counts."""
        t0 = time.monotonic()
        c0 = time.thread_time()
        with self._lock:
            armed = list(self._armed.values())
            exclude = set(self._exclude)
        exclude.add(threading.get_ident())
        frames = sys._current_frames()
        sampled = [i for i in frames if i not in exclude]
        unseen = [i for i in sampled if i not in self._kinfo]
        if t0 >= self._next_sweep:
            self._proc_sweep(sampled)
            cost = time.monotonic() - t0
            no_baseline = any(
                self._kinfo.get(i, ("", None, ""))[1] is None
                and i in self._cpu_prev
                for i in sampled)
            if no_baseline:
                self._next_sweep = t0 + self.BASELINE_SWEEP_S
            else:
                self._next_sweep = t0 + max(self.PROC_SWEEP_S,
                                            cost / self.SWEEP_DUTY)
        elif unseen:
            # Threads born since the last sweep (wire conn workers,
            # codec pool growth) would otherwise sample as unknown
            # until the adaptive cadence reaches them — sweep just the
            # newcomers, a bounded handful.
            self._proc_sweep(unseen)
        tick_cats: dict[str, int] = {}
        for ident in sampled:
            frame = frames[ident]
            marker = (id(frame), frame.f_lasti, id(frame.f_code))
            frozen = self._frame_state.get(ident) == marker
            self._frame_state[ident] = marker
            state, cpu_rate, wchan = self._kinfo.get(
                ident, ("", None, ""))
            category = classify_sample(
                frame, state, cpu_rate, frozen, wchan)
            stack = _format_stack(frame)
            for agg in armed:
                agg.add(category, stack)
            tick_cats[category] = tick_cats.get(category, 0) + 1
        for agg in armed:
            agg.ticks += 1
        for cat, n in tick_cats.items():
            PROF_SAMPLE_TICKS.inc(n, category=cat)
        now = time.monotonic()
        with self._lock:
            self._recent.append((now, tick_cats))
            cutoff = now - self.SHARE_WINDOW_S
            while self._recent and self._recent[0][0] < cutoff:
                self._recent.popleft()
        # The duty bound charges the tick's CPU time, not its wall
        # time: on a saturated box most of a tick's wall is the sampler
        # WAITING — for the GIL, or descheduled — which imposes no
        # overhead on the workload. Billing that starvation as cost
        # stretched the interval to seconds exactly when the workload
        # was busiest, and the phases that most needed samples (the
        # python-plane frame loop the obs lane profiles as its compare
        # baseline) closed with zero ticks.
        self._last_tick_cost = time.thread_time() - c0
        PROF_TICK_SECONDS.observe(now - t0)
        return tick_cats

    def recent_python_share(self) -> float | None:
        """python / (python + native) over the recent sample window —
        "how much of this process's on-CPU time is the frame loop",
        live. None when nothing sampled on-CPU recently. The window is
        re-cut against *now* on every read: once sampling stops (last
        phase disarmed) the share must expire, not freeze at its final
        value and masquerade as live for hours."""
        cutoff = time.monotonic() - self.SHARE_WINDOW_S
        with self._lock:
            recent = [(t, c) for t, c in self._recent if t >= cutoff]
        py = sum(c.get("python", 0) for _t, c in recent)
        native = sum(c.get("native", 0) for _t, c in recent)
        if py + native == 0:
            return None
        return py / (py + native)

    # -- output ---------------------------------------------------------------

    def _write(self, agg: PhaseAgg) -> None:
        text = agg.folded()
        if agg.out_dir:
            path = os.path.join(agg.out_dir, prof_file_name(agg.phase))
            try:
                tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(text)
                os.replace(tmp, path)
            except OSError as exc:
                log.warning("profiler artifact %s unwritable: %s",
                            path, exc)
        tee_dir = str(config.FLIGHT_DIR.get())
        if tee_dir:
            try:
                os.makedirs(tee_dir, exist_ok=True)
                import socket  # noqa: PLC0415 — tee path only

                tee = os.path.join(
                    tee_dir, f"prof-{socket.gethostname()}-{os.getpid()}"
                             f"-{agg.phase}.folded")
                with open(tee, "w", encoding="utf-8") as f:
                    f.write(text)
            except OSError:
                pass


_lock = threading.Lock()
_profiler: PhaseProfiler | None = None


def default_profiler() -> PhaseProfiler:
    global _profiler
    with _lock:
        if _profiler is None:
            _profiler = PhaseProfiler()
        return _profiler


def reset() -> None:
    """Drop the process profiler and ledger state (tests)."""
    global _profiler, _peak_codec_saturation
    with _lock:
        profiler, _profiler = _profiler, None
    if profiler is not None:
        profiler.stop()
    _ledger_state.reset()
    _peak_codec_saturation = 0.0


def on_flight_event(rec: Recorder, event: str) -> None:
    """Flight-recorder funnel hook: arm/disarm the profiler on the phase
    brackets :data:`PROFILED_PHASES` names. Called for EVERY recorded
    event — two dict misses when the event is not a profiled boundary.
    Never raises: observability must not take down the data path."""
    try:
        phase = _ARM_EVENTS.get(event)
        if phase is not None:
            default_profiler().arm(
                phase, os.path.dirname(rec.path), uid=rec.uid,
                role=rec.role)
            return
        phase = _DISARM_EVENTS.get(event)
        if phase is not None:
            default_profiler().disarm(phase)
    except Exception as exc:  # noqa: BLE001 — hot-path guard
        log.warning("profiler flight hook failed on %s: %s", event, exc)


# -- on-demand profile (debug server) -----------------------------------------


def sample_profile(seconds: float = 5.0, hz: float = 100.0) -> str:
    """Sample all threads for ``seconds`` at ``hz``; returns collapsed
    stacks sorted by sample count (descending). The debug-server
    endpoint (``/debug/pprof/profile``) — same sampling/classification
    engine as the phase profiler, the calling (handler) thread excluded,
    unique-stack cardinality capped."""
    seconds = min(max(seconds, 0.1), MAX_SECONDS)
    prof = PhaseProfiler(hz=hz)
    prof.exclude_thread(threading.get_ident())
    agg = PhaseAgg("ondemand", None, "", "", hz,
                   prof.max_stacks())
    with prof._lock:
        prof._armed["ondemand"] = agg
        prof._arm_depth["ondemand"] = 1
    deadline = time.monotonic() + seconds
    interval = 1.0 / hz if hz > 0 else 0.01
    while time.monotonic() < deadline:
        prof.sample_once()
        time.sleep(interval)
    total = agg.samples()
    lines = [
        f"{n} {cat};{stack}"
        for (cat, stack), n in sorted(agg.counts.items(),
                                      key=lambda kv: -kv[1])
    ]
    header = (
        f"# wall-clock samples: {total} over {seconds:.1f}s at {hz:.0f} Hz "
        f"({len(agg.counts)} unique stacks, "
        f"{agg.overflow} overflowed)\n"
    )
    return header + "\n".join(lines) + ("\n" if lines else "")


# -- resource ledger ----------------------------------------------------------


def read_process_resources() -> dict[str, float] | None:
    """One cumulative reading of this process's CPU/IO/RSS/ctx-switch
    counters from /proc; None when /proc is unavailable (non-Linux)."""
    try:
        with open("/proc/self/stat", "rb") as f:
            rest = f.read().rsplit(b")", 1)[1].split()
        tick = float(os.sysconf("SC_CLK_TCK") or 100)
        out = {
            "cpu_user_s": int(rest[11]) / tick,
            "cpu_sys_s": int(rest[12]) / tick,
        }
    except (OSError, IndexError, ValueError):
        return None
    try:
        with open("/proc/self/io", "rb") as f:
            for line in f.read().splitlines():
                if line.startswith(b"read_bytes:"):
                    out["io_read"] = int(line.split()[1])
                elif line.startswith(b"write_bytes:"):
                    out["io_write"] = int(line.split()[1])
    except (OSError, IndexError, ValueError):
        pass  # /proc/self/io needs CAP_SYS_PTRACE in some sandboxes
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f.read().splitlines():
                if line.startswith(b"VmRSS:"):
                    out["rss"] = int(line.split()[1]) * 1024
                elif line.startswith(b"voluntary_ctxt_switches:"):
                    out["vctx"] = int(line.split()[1])
                elif line.startswith(b"nonvoluntary_ctxt_switches:"):
                    out["ivctx"] = int(line.split()[1])
    except (OSError, IndexError, ValueError):
        pass
    return out


class LedgerState:
    """Windowed-rate derivation over consecutive cumulative readings.
    ``update`` is pure bookkeeping (two readings -> deltas/rates) so the
    delta math is unit-testable without /proc."""

    def __init__(self) -> None:
        self._prev: dict[str, float] | None = None
        self._prev_t: float = 0.0

    def reset(self) -> None:
        self._prev = None
        self._prev_t = 0.0

    def update(self, reading: dict[str, float],
               now: float) -> dict[str, float]:
        """Rates since the previous reading: ``cpuCores`` (CPU seconds
        per wall second), ``ioReadBps``/``ioWriteBps``. First call (no
        baseline) rates as 0."""
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = dict(reading), now
        dt = now - prev_t if prev is not None else 0.0
        if prev is None or dt <= 0:
            return {"cpuCores": 0.0, "ioReadBps": 0.0, "ioWriteBps": 0.0}

        def rate(key: str) -> float:
            if key not in reading or key not in prev:
                return 0.0
            return max(0.0, (reading[key] - prev[key]) / dt)

        return {
            "cpuCores": round(rate("cpu_user_s") + rate("cpu_sys_s"), 4),
            "ioReadBps": round(rate("io_read"), 1),
            "ioWriteBps": round(rate("io_write"), 1),
        }


_ledger_state = LedgerState()
_peak_codec_saturation = 0.0


def peak_codec_saturation() -> float:
    """Highest codec-pool saturation any ledger sample observed in this
    process (bench evidence: ``prof_codec_pool_saturation``)."""
    return _peak_codec_saturation


def sample_ledger() -> None:
    """One observability-sampler tick of the resource ledger: refresh
    the ``grit_prof_*`` gauges from /proc + the codec pool, and stamp
    the windowed rates (plus the profiler's live python share) into
    every live progress tracker so the snapshot/annotation/CRD path
    carries them to ``gritscope watch``."""
    global _peak_codec_saturation
    from grit_tpu import codec  # noqa: PLC0415 — jax-free, import-light

    reading = read_process_resources()
    sat = codec.pool_saturation()
    if sat is not None:
        PROF_CODEC_POOL_SATURATION.set(sat)
        _peak_codec_saturation = max(_peak_codec_saturation, sat)
    if reading is None:
        return
    PROF_CPU_SECONDS.set(reading["cpu_user_s"], mode="user")
    PROF_CPU_SECONDS.set(reading["cpu_sys_s"], mode="system")
    if "io_read" in reading:
        PROF_IO_BYTES.set(reading["io_read"], dir="read")
    if "io_write" in reading:
        PROF_IO_BYTES.set(reading["io_write"], dir="write")
    if "rss" in reading:
        PROF_RSS_BYTES.set(reading["rss"])
    if "vctx" in reading:
        PROF_CTX_SWITCHES.set(reading["vctx"], kind="voluntary")
    if "ivctx" in reading:
        PROF_CTX_SWITCHES.set(reading["ivctx"], kind="involuntary")
    ledger = _ledger_state.update(reading, time.monotonic())
    if "rss" in reading:
        ledger["rssBytes"] = reading["rss"]
    if sat is not None:
        ledger["codecSaturation"] = round(sat, 3)
    share = default_profiler().recent_python_share()
    if share is not None:
        ledger["pyShare"] = round(share, 3)
    from grit_tpu.obs import progress  # noqa: PLC0415

    for tracker in progress.trackers():
        tracker.set_ledger(ledger)
