"""Metrics/debug HTTP server (:10351 in the manager Deployment).

Parity: reference mounts the controller-runtime metrics server plus pprof
handlers on the same mux (``cmd/grit-manager/app/manager.go:83-92``,
``pkg/util/profile/profile.go:12-24``). Endpoints:

- ``/metrics`` — prometheus text exposition of :data:`grit_tpu.obs.REGISTRY`
- ``/debug/threadz`` — all-thread stack dump (pprof-goroutine analogue)
- ``/debug/pprof/profile?seconds=N`` — sampled CPU profile in
  collapsed-stack format (only when ``profiling=True``)
- ``/version`` — build stamp
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from grit_tpu.obs.metrics import REGISTRY, Registry, render_threadz


def start_metrics_server(
    port: int, host: str = "0.0.0.0", registry: Registry | None = None,
    *, profiling: bool = False,
) -> ThreadingHTTPServer:
    """Serve /metrics and /debug/threadz on ``port`` in a daemon thread.

    Returns the server (``.server_address[1]`` carries the bound port when
    ``port=0``; call ``.shutdown()`` to stop).
    """
    reg = registry or REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def _text(self, code: int, body: str,
                  content_type: str = "text/plain") -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self) -> None:  # noqa: N802 - http.server API
            url = urlparse(self.path)
            if url.path == "/metrics":
                self._text(
                    200, reg.render(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif url.path == "/debug/threadz":
                self._text(200, render_threadz())
            elif url.path == "/debug/pprof/profile" and profiling:
                from grit_tpu.obs.profile import sample_profile

                try:
                    seconds = float(
                        (parse_qs(url.query).get("seconds") or ["5"])[0]
                    )
                except ValueError:
                    self._text(400, "bad seconds\n")
                    return
                self._text(200, sample_profile(seconds))
            elif url.path == "/version":
                from grit_tpu.version import version_string

                self._text(200, version_string() + "\n")
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args: object) -> None:  # quiet
            return

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(
        target=srv.serve_forever, name="grit-metrics", daemon=True
    ).start()
    return srv


_workload_lock = threading.Lock()
_workload_srv: ThreadingHTTPServer | None = None


def start_workload_metrics_server() -> ThreadingHTTPServer | None:
    """Opt-in workload-side /metrics (``GRIT_WORKLOAD_METRICS_PORT``).

    Historically only the agent (``--metrics-port``) and the manager
    served a registry — but the restored pod's place latency, codec
    decode time and post-copy tail live in the WORKLOAD process, which
    made them unscrapeable during exactly the blackout window they
    measure. Called from the workload-side entry points (agentlet
    install, restore prefetch); idempotent per process, a no-op when the
    knob is unset, and never raises — a busy port must not take down a
    training step. Starts the periodic sampler alongside, so the
    workload's progress/queue-depth gauges stay fresh between events."""
    global _workload_srv
    from grit_tpu.api import config  # noqa: PLC0415

    port = int(config.WORKLOAD_METRICS_PORT.get())
    if port <= 0:
        return None
    with _workload_lock:
        if _workload_srv is not None:
            return _workload_srv
        try:
            srv = start_metrics_server(port)
        except OSError as exc:
            import logging  # noqa: PLC0415

            logging.getLogger(__name__).warning(
                "workload metrics server on port %d failed: %s "
                "(metrics stay process-local)", port, exc)
            return None
        _workload_srv = srv
    from grit_tpu.obs import sampler  # noqa: PLC0415

    sampler.start()
    return _workload_srv
