"""Metrics/debug HTTP server (:10351 in the manager Deployment).

Parity: reference mounts the controller-runtime metrics server plus pprof
handlers on the same mux (``cmd/grit-manager/app/manager.go:83-92``,
``pkg/util/profile/profile.go:12-24``). Endpoints:

- ``/metrics`` — prometheus text exposition of :data:`grit_tpu.obs.REGISTRY`
- ``/debug/threadz`` — all-thread stack dump (pprof-goroutine analogue)
- ``/debug/pprof/profile?seconds=N`` — sampled CPU profile in
  collapsed-stack format (only when ``profiling=True``)
- ``/version`` — build stamp
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from grit_tpu.obs.metrics import REGISTRY, Registry, render_threadz


def start_metrics_server(
    port: int, host: str = "0.0.0.0", registry: Registry | None = None,
    *, profiling: bool = False,
) -> ThreadingHTTPServer:
    """Serve /metrics and /debug/threadz on ``port`` in a daemon thread.

    Returns the server (``.server_address[1]`` carries the bound port when
    ``port=0``; call ``.shutdown()`` to stop).
    """
    reg = registry or REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def _text(self, code: int, body: str, content_type: str = "text/plain"):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 - http.server API
            url = urlparse(self.path)
            if url.path == "/metrics":
                self._text(
                    200, reg.render(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif url.path == "/debug/threadz":
                self._text(200, render_threadz())
            elif url.path == "/debug/pprof/profile" and profiling:
                from grit_tpu.obs.profile import sample_profile

                try:
                    seconds = float(
                        (parse_qs(url.query).get("seconds") or ["5"])[0]
                    )
                except ValueError:
                    return self._text(400, "bad seconds\n")
                self._text(200, sample_profile(seconds))
            elif url.path == "/version":
                from grit_tpu.version import version_string

                self._text(200, version_string() + "\n")
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):  # quiet
            return

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(
        target=srv.serve_forever, name="grit-metrics", daemon=True
    ).start()
    return srv
