"""Metrics/debug HTTP server (:10351 in the manager Deployment).

Parity: reference mounts the controller-runtime metrics server plus pprof
handlers on the same mux (``cmd/grit-manager/app/manager.go:83-92``,
``pkg/util/profile/profile.go:12-24``). Endpoints:

- ``/metrics`` — prometheus text exposition of :data:`grit_tpu.obs.REGISTRY`
- ``/debug/threadz`` — all-thread stack dump (pprof-goroutine analogue)
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from grit_tpu.obs.metrics import REGISTRY, Registry, render_threadz


def start_metrics_server(
    port: int, host: str = "0.0.0.0", registry: Registry | None = None
) -> ThreadingHTTPServer:
    """Serve /metrics and /debug/threadz on ``port`` in a daemon thread.

    Returns the server (``.server_address[1]`` carries the bound port when
    ``port=0``; call ``.shutdown()`` to stop).
    """
    reg = registry or REGISTRY

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path == "/metrics":
                body = reg.render().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/debug/threadz":
                body = render_threadz().encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *args):  # quiet
            return

    srv = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(
        target=srv.serve_forever, name="grit-metrics", daemon=True
    ).start()
    return srv
