"""Periodic observability sampler: keeps edge-triggered gauges fresh.

Several gauges used to update only when an event happened to fire
(``grit_codec_queue_depth`` on pool submission,
``grit_agent_heartbeat_age_seconds`` on a watchdog poll) — a Prometheus
scrape BETWEEN events read whatever edge last wrote, which for a queue
depth means "the backlog at some historical submission", not "the
backlog now". This module is the fix: one daemon thread per process,
ticking every ``GRIT_OBS_SAMPLE_S`` seconds, running a small set of
registered callbacks that re-derive those gauges from live state (and
refresh the migration progress gauges + snapshot files between lease
beats).

Shutdown is clean and bounded by construction: ``stop()`` sets an event
the loop waits on and joins with a timeout — no unbounded ``join()``,
no thread outliving the intent to stop it. Callbacks must never raise
out of the loop; one failing callback logs (once per callback) and the
rest keep sampling.
"""

from __future__ import annotations

import logging
import threading
from collections.abc import Callable

from grit_tpu.api import config

log = logging.getLogger(__name__)


def _sample_codec_queue_depth() -> None:
    from grit_tpu import codec  # noqa: PLC0415 — jax-free, import-light

    codec.sample_queue_depth()


def _sample_progress() -> None:
    from grit_tpu.obs import progress  # noqa: PLC0415

    progress.sample()


def _sample_ledger() -> None:
    from grit_tpu.obs import profile  # noqa: PLC0415

    profile.sample_ledger()


class Sampler:
    """Bounded-period callback loop on a daemon thread."""

    def __init__(self, period_s: float | None = None) -> None:
        self.period_s = max(
            0.05, float(period_s if period_s is not None
                        else config.OBS_SAMPLE_S.get()))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._callbacks: dict[str, Callable[[], None]] = {}
        self._warned: set[str] = set()

    def register(self, name: str, fn: Callable[[], None]) -> None:
        with self._lock:
            self._callbacks[name] = fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._callbacks.pop(name, None)

    def sample_once(self) -> None:
        with self._lock:
            callbacks = list(self._callbacks.items())
        for name, fn in callbacks:
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 — one bad cb ≠ dead loop
                if name not in self._warned:
                    self._warned.add(name)
                    log.warning("sampler callback %s failing: %s", name, exc)

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            self.sample_once()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Sampler":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="grit-obs-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 2.0, final_sample: bool = True) -> None:
        """Signal the loop and join BOUNDED (the clean-daemon-shutdown
        contract: a wedged callback must not pin the caller). A final
        synchronous sample by default, so short runs still publish their
        terminal state."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)
            if thread.is_alive():
                log.warning("obs sampler did not stop within %.1fs "
                            "(daemon thread; abandoning it)", timeout)
            self._thread = None
        if final_sample:
            self.sample_once()

    def __enter__(self) -> "Sampler":
        return self.start()

    def __exit__(self, *exc: object) -> None:
        self.stop()


_lock = threading.Lock()
_sampler: Sampler | None = None


def default_sampler() -> Sampler:
    """The process-wide sampler, with the default callback set (codec
    queue depth + migration progress) pre-registered. Not started —
    callers own the lifecycle (agent run, manager runtime, workload
    metrics server)."""
    global _sampler
    with _lock:
        if _sampler is None:
            _sampler = Sampler()
            _sampler.register("codec-queue-depth",
                              _sample_codec_queue_depth)
            # Ledger BEFORE progress: the ledger stamp rides the same
            # tick's snapshot publish instead of trailing one period.
            _sampler.register("resource-ledger", _sample_ledger)
            _sampler.register("migration-progress", _sample_progress)
        return _sampler


def start() -> Sampler:
    return default_sampler().start()


def stop(timeout: float = 2.0) -> None:
    with _lock:
        sampler = _sampler
    if sampler is not None:
        sampler.stop(timeout=timeout)


def reset() -> None:
    """Drop the global sampler (tests)."""
    global _sampler
    with _lock:
        sampler, _sampler = _sampler, None
    if sampler is not None:
        sampler.stop(final_sample=False)
