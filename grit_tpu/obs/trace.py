"""Distributed tracing for the migration path.

The product of this system is a latency budget (<60 s blackout,
BASELINE.md): spans over quiesce → dump → upload → stage → restore are
operational necessity, not polish. Reference analogue: the shim's
build-tag-gated OTEL tracing (``cmd/containerd-shim-grit-v1/
main_tracing.go:19-24``) and per-shim ``OTEL_SERVICE_NAME``
(``manager/manager_linux.go:107``) — generalized here to the whole
control plane, which the reference never traced at all.

Design:

- **Noop by default.** Tracing turns on only when ``GRIT_TPU_TRACE_FILE``
  names a JSONL sink (one OTLP-shaped span dict per line) — the exporter
  a zero-egress cluster can always afford. When the ``opentelemetry`` API
  is importable and an SDK provider is installed, spans are mirrored
  through it too, so a real OTLP pipeline needs no code change.
- **W3C context propagation.** One migration is ONE trace across four
  processes. The trace context crosses boundaries the same way the rest
  of GRIT coordinates (SURVEY §1 "coordination by annotation + sentinel
  file"): manager stamps ``grit.dev/traceparent`` on the CR, the agent
  Job carries ``TRACEPARENT`` in its env (the W3C env convention), and
  the pod annotation passthrough hands it to the shim.
- **Threading.** The current span is thread-local; background threads
  start their own roots unless given an explicit parent.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Mapping, TextIO

from grit_tpu.api import config
from grit_tpu.api.constants import TRACEPARENT_ANNOTATION  # noqa: F401 — re-export

TRACEPARENT_ENV = "TRACEPARENT"
TRACE_FILE_ENV = config.TPU_TRACE_FILE.name

_local = threading.local()
_lock = threading.Lock()


def enabled() -> bool:
    return bool(config.TPU_TRACE_FILE.get())


@dataclass
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


@dataclass
class Span:
    name: str
    context: SpanContext
    parent_span_id: str | None
    start_ns: int
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "OK"

    def set_attribute(self, key: str, value: object) -> None:
        self.attributes[key] = value


def _current() -> Span | None:
    return getattr(_local, "span", None)


def parse_traceparent(value: str) -> SpanContext | None:
    """``00-<trace>-<span>-<flags>`` → SpanContext; None if malformed."""
    parts = value.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return SpanContext(trace_id=parts[1], span_id=parts[2])


def current_traceparent() -> str | None:
    """The active span's W3C traceparent, for manual propagation."""
    span = _current()
    return span.context.traceparent() if span else None


def current_context() -> SpanContext | None:
    """The calling thread's effective parent context: the active span's,
    or the fallback installed by :func:`parented`. Capture this BEFORE
    handing work to a pool/background thread — the span stack is
    thread-local, so without it every pooled span roots a new trace."""
    span = _current()
    if span is not None:
        return span.context
    return getattr(_local, "parent_ctx", None)


@contextmanager
def parented(ctx: SpanContext | None) -> Iterator[None]:
    """Install ``ctx`` as this thread's fallback parent for the duration.

    The hand-off half of cross-thread propagation: the submitting thread
    captures :func:`current_context` and the worker runs inside
    ``parented(ctx)`` — spans (and :func:`record_span`) opened there join
    the migration trace instead of rooting their own. Nests safely (the
    previous fallback is restored) and is a no-op for ``ctx=None``."""
    prev = getattr(_local, "parent_ctx", None)
    _local.parent_ctx = ctx if ctx is not None else prev
    try:
        yield
    finally:
        _local.parent_ctx = prev


def wrap_parented(fn: Callable[..., Any],
                  ctx: SpanContext | None = None) -> Callable[..., Any]:
    """Bind ``fn`` to the submitting thread's trace context: returns a
    callable that runs ``fn`` under :func:`parented`. The one-line seam
    pool submissions thread the parent through (codec pool, mirror
    writer)."""
    if ctx is None:
        ctx = current_context()
    if ctx is None:
        return fn

    def run(*args: Any, **kwargs: Any) -> Any:
        with parented(ctx):
            return fn(*args, **kwargs)

    return run


def inject_env(env: Mapping[str, str] | None = None) -> dict[str, str]:
    """Add ``TRACEPARENT`` for a child process (no-op when not tracing)."""
    env = dict(env or {})
    tp = current_traceparent()
    if tp:
        env[TRACEPARENT_ENV] = tp
    return env


def extract_parent(
        environ: Mapping[str, str] | None = None) -> SpanContext | None:
    """Remote parent from ``TRACEPARENT`` in the (process) environment."""
    environ = environ if environ is not None else os.environ
    raw = environ.get(TRACEPARENT_ENV, "")
    return parse_traceparent(raw) if raw else None


def _service_name() -> str:
    return os.environ.get("OTEL_SERVICE_NAME", "grit-tpu")


# Export sink state, all under _lock: a cached append handle (one open
# per sink, not one per span — the old per-span open was measurable on
# chunk-hot paths), plus a retry clock so a failed sink RECOVERS on a
# later successful open instead of latching broken for the process
# lifetime (the disk-full-then-cleared case).
_sink_path: str | None = None
_sink_file: TextIO | None = None
_sink_retry_at = 0.0
_SINK_RETRY_S = 5.0
_sink_warned = False
_sink_check_at = 0.0
_SINK_CHECK_S = 5.0


def _sink_stale_locked() -> bool:
    """True when the cached handle no longer backs the sink path (the
    file was rotated/deleted): the open-per-span code recreated it
    implicitly; the cached handle must notice, at a coarse interval, or
    every later span writes to an orphaned inode forever."""
    global _sink_check_at
    now = time.monotonic()
    if now < _sink_check_at:
        return False
    _sink_check_at = now + _SINK_CHECK_S
    try:
        disk = os.stat(_sink_path)
        here = os.fstat(_sink_file.fileno())
        return (disk.st_ino, disk.st_dev) != (here.st_ino, here.st_dev)
    except OSError:
        return True  # unlinked (or handle broken): reopen


def _sink_open_locked(path: str) -> TextIO | None:
    """(Re)open the sink for append, healing the torn-line boundary: a
    writer killed mid-line leaves the file without a trailing newline,
    and a new record appended raw would glue onto the torn line — both
    records would then be lost to every reader. Start on a fresh line."""
    global _sink_path, _sink_file
    if _sink_file is not None and _sink_path == path \
            and not _sink_stale_locked():
        return _sink_file
    if _sink_file is not None:
        try:
            _sink_file.close()
        except OSError:
            pass
        _sink_file = None
    needs_newline = False
    try:
        with open(path, "rb") as probe:
            probe.seek(0, os.SEEK_END)
            if probe.tell() > 0:
                probe.seek(-1, os.SEEK_END)
                needs_newline = probe.read(1) != b"\n"
    except OSError:
        pass  # absent file: nothing to heal
    f = open(path, "a")
    if needs_newline:
        f.write("\n")
    _sink_path, _sink_file = path, f
    return f


def _sink_close_locked() -> None:
    global _sink_path, _sink_file
    if _sink_file is not None:
        try:
            _sink_file.close()
        except OSError:
            pass
    _sink_path, _sink_file = None, None


def close_export() -> None:
    """Close the cached sink handle (tests flip the sink path; a process
    about to exec should flush)."""
    with _lock:
        _sink_close_locked()


def _export(span: Span, end_ns: int) -> None:
    global _sink_retry_at, _sink_warned
    path = config.TPU_TRACE_FILE.get()
    if not path:
        return
    record = {
        "traceId": span.context.trace_id,
        "spanId": span.context.span_id,
        "parentSpanId": span.parent_span_id or "",
        "name": span.name,
        "startTimeUnixNano": span.start_ns,
        "endTimeUnixNano": end_ns,
        "serviceName": _service_name(),
        "status": span.status,
        "attributes": span.attributes,
    }
    line = json.dumps(record, default=str) + "\n"
    with _lock:
        if _sink_file is None and time.monotonic() < _sink_retry_at:
            return  # sink recently failed; back off, retry soon
        try:
            f = _sink_open_locked(path)
            f.write(line)
            f.flush()
            if _sink_warned:
                _sink_warned = False
                import logging

                logging.getLogger(__name__).warning(
                    "trace sink %s recovered; tracing resumed", path)
            return
        except OSError as e:
            # Observability must never take down the data path (and must
            # not mask an in-flight exception from span()'s finally):
            # drop this span, close the handle, and retry the open after
            # a short backoff — a cleared disk recovers the sink instead
            # of the old latched-forever disable.
            _sink_close_locked()
            _sink_retry_at = time.monotonic() + _SINK_RETRY_S
            if not _sink_warned:
                _sink_warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "trace sink %s unwritable (%s); dropping spans, will "
                    "retry in %.0fs", path, e, _SINK_RETRY_S)


@contextmanager
def span(name: str, parent: SpanContext | None = None,
         **attributes: object) -> "Iterator[Span | _NoopSpan]":
    """Context manager for one span. Near-zero cost when disabled (one
    env lookup); exceptions mark the span ERROR and re-raise."""
    if not enabled():
        yield _NOOP_SPAN
        return
    prev = _current()
    if parent is None and prev is not None:
        parent = prev.context
    if parent is None:
        # Cross-thread fallback (parented()): pool/background threads
        # join the submitting thread's trace instead of rooting new ones.
        parent = getattr(_local, "parent_ctx", None)
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
    )
    s = Span(
        name=name,
        context=ctx,
        parent_span_id=parent.span_id if parent else None,
        start_ns=time.time_ns(),
        attributes=dict(attributes),
    )
    _local.span = s
    # Mirror through the OTEL API when an SDK provider is installed
    # (the bare API's default provider is a noop — costless).
    otel_cm = None
    try:  # pragma: no cover - depends on environment SDK
        from opentelemetry import trace as otel_trace

        otel_cm = otel_trace.get_tracer("grit_tpu").start_as_current_span(
            name)
        otel_cm.__enter__()
    except Exception:
        otel_cm = None
    try:
        yield s
    except BaseException:
        s.status = "ERROR"
        raise
    finally:
        if otel_cm is not None:
            try:  # pragma: no cover
                otel_cm.__exit__(None, None, None)
            except Exception:  # noqa: BLE001 — OTEL mirror is best-effort
                pass
        _local.span = prev
        _export(s, time.time_ns())


def record_span(name: str, start_unix_ns: int, *,
                parent: SpanContext | None = None,
                status: str = "OK", **attributes: object) -> None:
    """Export a span retroactively (no context management) — for hot
    paths that already time themselves and must not grow an indent level.
    Joins the calling thread's current span when no parent is given."""
    if not enabled():
        return
    cur = _current()
    if parent is None and cur is not None:
        parent = cur.context
    if parent is None:
        parent = getattr(_local, "parent_ctx", None)
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
    )
    s = Span(name=name, context=ctx,
             parent_span_id=parent.span_id if parent else None,
             start_ns=start_unix_ns, attributes=dict(attributes),
             status=status)
    _export(s, time.time_ns())


class _NoopSpan:
    __slots__ = ()

    def set_attribute(self, key: str, value: object) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


def read_trace_file(path: str) -> list[dict[str, Any]]:
    """Parse a JSONL trace sink (test/docs helper). Malformed lines are
    skipped, not fatal: several processes append under per-process locks
    only, so a torn line at a crash boundary must not poison the whole
    trace."""
    out: list[dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
