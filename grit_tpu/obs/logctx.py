"""Log correlation: stamp the migration uid + role onto every record.

The flight recorder keys everything by the migration uid, but node logs
(agent Job stdout, workload pod logs) carried no uid at all — joining a
log line to a ``gritscope`` timeline meant grepping by wall clock. This
module closes that gap with two small pieces:

- a **log-record factory wrapper** that stamps ``grit_uid`` /
  ``grit_role`` (from the process's configured flight recorder — the
  same context every flight event carries) onto EVERY record, whichever
  logger it came from. A factory beats a ``logging.Filter`` here:
  filters attached to a logger only see records logged *directly* on
  it, never on its children, and per-handler filters miss records that
  never reach that handler;
- a **formatter wrapper** that appends ``[uid=... role=...]`` to the
  rendered line when (and only when) a migration context exists, so an
  idle process's logs stay clean and a migration's logs join the
  ``gritscope`` timeline with one grep.

Installed by the agent CLI, the restored pod's prefetch hook, and the
agentlet install path (:func:`install_log_correlation` is idempotent
and never raises — logging plumbing must not take down a data-path
leg). ``MigrationLogFilter`` is also exported for operators who wire
their own handlers/formatters and want just the attributes.
"""

from __future__ import annotations

import logging
import threading

from grit_tpu.obs import flight

_lock = threading.Lock()
_installed = False


def _context() -> tuple[str, str]:
    """(uid, role) of this process's live migration, or ("", "").
    ``flight.active()``, not ``current()``: workload and restored-pod
    processes never call configure() — they join the migration through
    emit_near's walk-up, and correlation must cover exactly them."""
    rec = flight.active()
    if rec is None:
        return "", ""
    return rec.uid, rec.role


class MigrationLogFilter(logging.Filter):
    """Stamps ``grit_uid``/``grit_role`` and always passes the record —
    attach to a handler when the factory route is not available (tests,
    operator-managed logging trees)."""

    def filter(self, record: logging.LogRecord) -> bool:
        uid, role = _context()
        record.grit_uid = uid
        record.grit_role = role
        return True


class CorrelationFormatter(logging.Formatter):
    """Wraps another formatter, appending the migration context to the
    rendered line when one exists."""

    def __init__(self, inner: logging.Formatter | None = None) -> None:
        super().__init__()
        self._inner = inner or logging.Formatter()

    def format(self, record: logging.LogRecord) -> str:
        line = self._inner.format(record)
        uid = getattr(record, "grit_uid", "")
        if uid:
            role = getattr(record, "grit_role", "")
            line += f" [uid={uid} role={role}]"
        return line


def install_log_correlation(ensure_handler: bool = False) -> None:
    """Idempotent process-wide install: wrap the record factory (stamp
    attributes on every record) and the rendering path (append the
    context to rendered lines).

    Rendering covers three situations: root handlers that already
    exist get their formatter wrapped; a process with NO root handlers
    (the common case — the grit tree never calls basicConfig) renders
    through ``logging.lastResort``, so that handler is wrapped too; and
    an application entry point that owns its process (the agent CLI)
    passes ``ensure_handler=True`` to install a stderr handler
    outright — a library context (agentlet inside a user's workload)
    must NOT, because adding a root handler would double every line the
    workload's own logging setup later produces."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
        try:
            factory = logging.getLogRecordFactory()

            def _with_context(*args: object, **kwargs: object) -> logging.LogRecord:
                record = factory(*args, **kwargs)
                uid, role = _context()
                record.grit_uid = uid
                record.grit_role = role
                return record

            logging.setLogRecordFactory(_with_context)
            root = logging.getLogger()
            if ensure_handler and not root.handlers:
                root.addHandler(logging.StreamHandler())
            for handler in root.handlers:
                if not isinstance(handler.formatter, CorrelationFormatter):
                    handler.setFormatter(
                        CorrelationFormatter(handler.formatter))
            last = logging.lastResort
            if last is not None \
                    and not isinstance(last.formatter,
                                       CorrelationFormatter):
                last.setFormatter(CorrelationFormatter(last.formatter))
        except Exception as exc:  # noqa: BLE001 — logging must not kill a leg
            logging.getLogger(__name__).warning(
                "log correlation install failed: %s", exc)


def reset() -> None:
    """Forget the install flag (tests). Does not unwrap the factory —
    the wrapper is idempotent and stamps empty strings when no
    migration is configured."""
    global _installed
    with _lock:
        _installed = False
