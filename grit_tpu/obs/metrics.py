"""Prometheus-text metrics registry (manager + agent observability).

Parity: the reference mounts controller-runtime's metrics server on :10351
(``cmd/grit-manager/app/manager.go:83-92``) but defines zero custom metrics;
we go further and instrument what the product actually promises — phase
transitions, transfer throughput, snapshot bytes/seconds, and the blackout
window — because "blackout < 60 s" is unverifiable without them.

No prometheus_client dependency: the exposition format is a stable text
protocol, trivially rendered by hand. Only the metric families the control
plane needs are implemented (counter, gauge, summary-style pairs).
"""

from __future__ import annotations

import threading
from typing import Iterable


def _fmt_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in labels
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    def __init__(self, name: str, help_: str, kind: str, labelnames: Iterable[str]):
        self.name = name
        self.help = help_
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name}: labels {sorted(labels)} != "
                f"declared {sorted(self.labelnames)}"
            )
        return tuple((k, str(labels[k])) for k in self.labelnames)

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        for key, val in items:
            lines.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(val)}")
        return "\n".join(lines)


class Counter(_Metric):
    def __init__(self, name, help_, labelnames=()):
        super().__init__(name, help_, "counter", labelnames)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Gauge(_Metric):
    def __init__(self, name, help_, labelnames=()):
        super().__init__(name, help_, "gauge", labelnames)

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help_: str, labelnames) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, labelnames)
                self._metrics[name] = m
            elif not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                raise ValueError(f"metric {name} re-registered with a different shape")
            return m

    def counter(self, name: str, help_: str, labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help_, labelnames)  # type: ignore[return-value]

    def gauge(self, name: str, help_: str, labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, labelnames)  # type: ignore[return-value]

    def render(self) -> str:
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        return "\n".join(m.render() for m in metrics) + "\n"


REGISTRY = Registry()

# -- the product's metric set -------------------------------------------------

PHASE_TRANSITIONS = REGISTRY.counter(
    "grit_phase_transitions_total",
    "Checkpoint/Restore CR phase transitions observed by the controllers",
    ("kind", "phase"),
)
RECONCILE_ERRORS = REGISTRY.counter(
    "grit_reconcile_errors_total",
    "Reconcile attempts that returned an error, per controller",
    ("controller",),
)

DRAIN_MIGRATIONS = REGISTRY.counter(
    "grit_drain_migrations_total",
    "Drain-triggered migration decisions (created / skipped_*)",
    ("outcome",),
)
TRANSFER_BYTES = REGISTRY.counter(
    "grit_transfer_bytes_total",
    "Bytes moved by the agent data mover (checkpoint upload / restore download)",
    ("direction",),
)
TRANSFER_SECONDS = REGISTRY.counter(
    "grit_transfer_seconds_total",
    "Wall seconds spent in the agent data mover",
    ("direction",),
)
SNAPSHOT_BYTES = REGISTRY.counter(
    "grit_snapshot_bytes_total",
    "Bytes written/read by the HBM snapshot engine",
    ("op",),
)
SNAPSHOT_SECONDS = REGISTRY.counter(
    "grit_snapshot_seconds_total",
    "Wall seconds spent writing/reading HBM snapshots",
    ("op",),
)
RESTORE_PIPELINE_SECONDS = REGISTRY.counter(
    "grit_restore_pipeline_seconds_total",
    "Summed per-leg durations of the restore data path (stage_wait = "
    "blocked on the streamed-staging journal, read = disk+checksum, "
    "place = host-to-device puts); wall clock overlaps these legs",
    ("phase",),
)
RESTORE_OVERLAP_FRACTION = REGISTRY.gauge(
    "grit_restore_overlap_fraction",
    "1 - wall/(stage_wait+read+place) of the most recent restore: the "
    "fraction of serial leg time the pipelined restore hid",
)
WIRE_BYTES = REGISTRY.counter(
    "grit_wire_bytes_total",
    "Bytes moved over the direct source-to-destination migration wire",
    ("role",),  # send | recv
)
WIRE_SECONDS = REGISTRY.counter(
    "grit_wire_seconds_total",
    "Wall seconds of the wire leg, by phase: send = socket writes, "
    "stall = producer blocked on the bounded send queue (slow consumer "
    "backpressure), ack = waiting for the destination's commit ack",
    ("phase",),
)
WIRE_FALLBACKS = REGISTRY.counter(
    "grit_wire_fallbacks_total",
    "Wire-mode migrations that fell back to the PVC double-hop, by the "
    "stage the wire died in",
    ("stage",),  # connect | dump | send | commit | receive
)
CODEC_BYTES = REGISTRY.counter(
    "grit_codec_bytes_total",
    "Bytes through the snapshot-transport codec stage, by direction: "
    "compress_in/compress_out = raw/compressed bytes of blocks that "
    "shipped compressed, compress_raw_shipped = raw bytes the adaptive "
    "sampler decided to ship uncompressed, decompress_in/decompress_out "
    "= compressed/raw bytes decoded on the receive side",
    ("dir", "codec"),
)
CODEC_SECONDS = REGISTRY.counter(
    "grit_codec_seconds_total",
    "Summed worker seconds spent in codec compute (sampling + "
    "compress, or decompress + CRC), by direction; the pool overlaps "
    "this with transport, so compare against wire/transfer seconds to "
    "see whether the codec hid inside the data path",
    ("dir",),
)
CODEC_QUEUE_DEPTH = REGISTRY.gauge(
    "grit_codec_queue_depth",
    "Jobs queued (not yet picked up) in the shared codec worker pool at "
    "the most recent submission — sustained depth means the codec stage, "
    "not the transport, is the bottleneck of the dump/receive path",
)
FLIGHT_EVENTS = REGISTRY.counter(
    "grit_flight_events_total",
    "Flight-recorder events emitted by this process, by phase family "
    "(the first dotted segment of the event name — a closed vocabulary "
    "from grit_tpu.obs.flight.EVENTS)",
    ("phase",),
)
CODEC_RATIO = REGISTRY.gauge(
    "grit_codec_ratio",
    "compressed/raw byte ratio of the most recent dump transport "
    "session (adaptive raw-shipped blocks count at 1.0), per direction "
    "of travel on this node",
)
WIRE_OVERLAP_FRACTION = REGISTRY.gauge(
    "grit_wire_overlap_fraction",
    "Fraction of the most recent wire session's bytes that reached the "
    "socket while the HBM dump was still draining (dump/send overlap)",
)
BLACKOUT_SECONDS = REGISTRY.gauge(
    "grit_last_blackout_seconds",
    "Duration of the most recent checkpoint blackout window "
    "(device quiesce through resume) on this node agent",
)
CHECKPOINTS_TOTAL = REGISTRY.counter(
    "grit_agent_checkpoints_total",
    "Pod checkpoints executed by this node agent",
    ("outcome",),
)
MIGRATION_ABORTS = REGISTRY.counter(
    "grit_migration_aborts_total",
    "Migration legs aborted back to a resumed source (driver=manager "
    "counts control-plane abort decisions; driver=agent counts node-side "
    "abort executions — one production abort increments both once)",
    ("driver",),
)
SOURCE_RESUME_SECONDS = REGISTRY.gauge(
    "grit_source_resume_seconds",
    "Wall seconds the most recent abort took from abort start until the "
    "source workload was unquiesced and resumable",
)
HEARTBEAT_AGE = REGISTRY.gauge(
    "grit_agent_heartbeat_age_seconds",
    "Age of the most recently observed agent-Job heartbeat lease, per CR "
    "kind (grit.dev/heartbeat annotation; Job creation time before the "
    "first renewal)",
    ("kind",),
)
AGENT_JOB_RETRIES = REGISTRY.counter(
    "grit_agent_job_retries_total",
    "Agent-Job re-creations scheduled by the manager watchdog, by CR "
    "kind and detection cause",
    ("kind", "cause"),
)


def render_threadz() -> str:
    """Stack dump of all live threads (the pprof-goroutine analogue;
    reference mounts pprof at app/manager.go:88-92)."""
    import sys
    import traceback

    frames = sys._current_frames()
    out = []
    for thread in threading.enumerate():
        frame = frames.get(thread.ident)
        out.append(f"--- thread {thread.name} (daemon={thread.daemon}) ---")
        if frame is not None:
            out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"
