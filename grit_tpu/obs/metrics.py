"""Prometheus-text metrics registry (manager + agent observability).

Parity: the reference mounts controller-runtime's metrics server on :10351
(``cmd/grit-manager/app/manager.go:83-92``) but defines zero custom metrics;
we go further and instrument what the product actually promises — phase
transitions, transfer throughput, snapshot bytes/seconds, and the blackout
window — because "blackout < 60 s" is unverifiable without them.

No prometheus_client dependency: the exposition format is a stable text
protocol, trivially rendered by hand. Only the metric families the control
plane needs are implemented (counter, gauge, histogram, summary-style
pairs).
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Mapping, TypeVar


_M = TypeVar("_M", bound="_Metric")


def _fmt_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in labels
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 labelnames: Iterable[str] = ()) -> None:
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Mapping[str, object]) -> tuple[tuple[str, str], ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name}: labels {sorted(labels)} != "
                f"declared {sorted(self.labelnames)}"
            )
        return tuple((k, str(labels[k])) for k in self.labelnames)

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.kind}",
        ]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.labelnames:
            items = [((), 0.0)]
        for key, val in items:
            lines.append(f"{self.name}{_fmt_labels(key)} {_fmt_value(val)}")
        return "\n".join(lines)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: object) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: object) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def remove(self, **labels: object) -> None:
        """Drop one label set's series (the subject is gone — a
        completed migration's heartbeat age has no meaning, and a gauge
        actively aged forever would alert on an idle manager)."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def value(self, **labels: object) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (the prometheus classic): per label
    set, one counter per ``le`` boundary plus ``_sum``/``_count``.
    Bucket boundaries are DECLARED here, bounded and literal — the
    ``metrics-contract`` lint rejects dynamic or unbounded bucket lists,
    because every boundary is a time series forever."""

    MAX_BUCKETS = 24
    kind = "histogram"

    def __init__(self, name: str, help_: str, buckets: Iterable[float],
                 labelnames: Iterable[str] = ()) -> None:
        super().__init__(name, help_, labelnames)
        bounds = tuple(float(b) for b in buckets)
        if not bounds or len(bounds) > self.MAX_BUCKETS:
            raise ValueError(
                f"histogram {name}: needs 1..{self.MAX_BUCKETS} bucket "
                f"boundaries, got {len(bounds)}")
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(
                f"histogram {name}: bucket boundaries must be strictly "
                "increasing")
        self.buckets = bounds
        # key -> [counts per bound (+inf implicit), sum, count]
        self._hist: dict[tuple[tuple[str, str], ...], list[Any]] = {}

    def observe(self, value: float, **labels: object) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            slot = self._hist.get(key)
            if slot is None:
                slot = [[0] * (len(self.buckets) + 1), 0.0, 0]
                self._hist[key] = slot
            counts, _sum, _n = slot
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    counts[i] += 1
                    break
            else:
                counts[len(self.buckets)] += 1
            slot[1] += v
            slot[2] += 1

    def count(self, **labels: object) -> int:
        with self._lock:
            slot = self._hist.get(self._key(labels))
            return int(slot[2]) if slot else 0

    def sum(self, **labels: object) -> float:
        with self._lock:
            slot = self._hist.get(self._key(labels))
            return float(slot[1]) if slot else 0.0

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            items = sorted((k, (list(v[0]), v[1], v[2]))
                           for k, v in self._hist.items())
        for key, (counts, total, n) in items:
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                le = _fmt_value(bound)
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(key + (('le', le),))} {cum}")
            cum += counts[-1]
            lines.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(key + (('le', '+Inf'),))} {cum}")
            lines.append(
                f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(total)}")
            lines.append(f"{self.name}_count{_fmt_labels(key)} {n}")
        return "\n".join(lines)


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls: type[_M], name: str, help_: str,
                       labelnames: Iterable[str]) -> _M:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, labelnames)
                self._metrics[name] = m
            elif not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                raise ValueError(f"metric {name} re-registered with a different shape")
            return m

    def counter(self, name: str, help_: str,
                labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_, labelnames)

    def gauge(self, name: str, help_: str,
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, labelnames)

    def histogram(self, name: str, help_: str, buckets: Iterable[float],
                  labelnames: Iterable[str] = ()) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets, labelnames)
                self._metrics[name] = m
            elif not isinstance(m, Histogram) \
                    or m.labelnames != tuple(labelnames) \
                    or m.buckets != tuple(float(b) for b in buckets):
                raise ValueError(
                    f"metric {name} re-registered with a different shape")
            return m

    def render(self) -> str:
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        return "\n".join(m.render() for m in metrics) + "\n"


REGISTRY = Registry()

# -- the product's metric set -------------------------------------------------

PHASE_TRANSITIONS = REGISTRY.counter(
    "grit_phase_transitions_total",
    "Checkpoint/Restore CR phase transitions observed by the controllers",
    ("kind", "phase"),
)
RECONCILE_ERRORS = REGISTRY.counter(
    "grit_reconcile_errors_total",
    "Reconcile attempts that returned an error, per controller",
    ("controller",),
)

DRAIN_MIGRATIONS = REGISTRY.counter(
    "grit_drain_migrations_total",
    "Drain-triggered migration decisions (created / skipped_*)",
    ("outcome",),
)
TRANSFER_BYTES = REGISTRY.counter(
    "grit_transfer_bytes_total",
    "Bytes moved by the agent data mover (checkpoint upload / restore download)",
    ("direction",),
)
TRANSFER_SECONDS = REGISTRY.counter(
    "grit_transfer_seconds_total",
    "Wall seconds spent in the agent data mover",
    ("direction",),
)
SNAPSHOT_BYTES = REGISTRY.counter(
    "grit_snapshot_bytes_total",
    "Bytes written/read by the HBM snapshot engine",
    ("op",),
)
SNAPSHOT_SECONDS = REGISTRY.counter(
    "grit_snapshot_seconds_total",
    "Wall seconds spent writing/reading HBM snapshots",
    ("op",),
)
SNAP_SPECULATIVE_BYTES = REGISTRY.counter(
    "grit_snap_speculative_bytes_total",
    "Validated-speculation byte accounting at the parked re-ship: clean "
    "= bytes the speculative pass already shipped that validation let "
    "the re-ship reference (zero device reads), dirty = bytes the "
    "in-flight step touched that had to re-ship inside the window",
    ("outcome",),  # clean | dirty
)
SNAP_SPECULATIVE_SECONDS = REGISTRY.counter(
    "grit_snap_speculative_seconds_total",
    "Wall seconds of the speculative dump machinery: concurrent = the "
    "speculative pass overlapping execution (outside the park), "
    "validate = the per-array device compare at the step boundary",
    ("phase",),  # concurrent | validate
)
SNAP_SPECULATIVE_ROUNDS = REGISTRY.counter(
    "grit_snap_speculative_rounds_total",
    "Speculative dump outcomes: validated = parked re-ship referenced "
    "the speculative pass, degraded = speculation lost (fault, timeout, "
    "structure change) and the dump fell back to the parked full path, "
    "probe = non-parking standby probe served entirely speculatively",
    ("outcome",),  # validated | degraded | probe
)
RESTORE_PIPELINE_SECONDS = REGISTRY.counter(
    "grit_restore_pipeline_seconds_total",
    "Summed per-leg durations of the restore data path (stage_wait = "
    "blocked on the streamed-staging journal, read = disk+checksum, "
    "place = host-to-device puts); wall clock overlaps these legs",
    ("phase",),
)
RESTORE_OVERLAP_FRACTION = REGISTRY.gauge(
    "grit_restore_overlap_fraction",
    "1 - wall/(stage_wait+read+place) of the most recent restore: the "
    "fraction of serial leg time the pipelined restore hid",
)
WIRE_BYTES = REGISTRY.counter(
    "grit_wire_bytes_total",
    "Bytes moved over the direct source-to-destination migration wire",
    ("role",),  # send | recv
)
WIRE_SECONDS = REGISTRY.counter(
    "grit_wire_seconds_total",
    "Wall seconds of the wire leg, by phase: send = socket writes, "
    "stall = producer blocked on the bounded send queue (slow consumer "
    "backpressure), ack = waiting for the destination's commit ack",
    ("phase",),
)
WIRE_FALLBACKS = REGISTRY.counter(
    "grit_wire_fallbacks_total",
    "Wire-mode migrations that fell back to the PVC double-hop, by the "
    "stage the wire died in",
    ("stage",),  # connect | dump | send | commit | receive
)
WIRE_NATIVE_BYTES = REGISTRY.counter(
    "grit_wire_native_bytes_total",
    "Payload bytes that moved through the native (libgritio) wire data "
    "plane instead of the Python frame loop, by path: send_ring = "
    "dump-mirror/codec frames staged into the C ring-buffer send "
    "worker, send_file = file bytes shipped sendfile(2) without "
    "entering userspace, recv = frames decoded, CRC-verified and "
    "pwritten natively on the receive side",
    ("path",),  # send_ring | send_file | recv
)
CODEC_BYTES = REGISTRY.counter(
    "grit_codec_bytes_total",
    "Bytes through the snapshot-transport codec stage, by direction: "
    "compress_in/compress_out = raw/compressed bytes of blocks that "
    "shipped compressed, compress_raw_shipped = raw bytes the adaptive "
    "sampler decided to ship uncompressed, decompress_in/decompress_out "
    "= compressed/raw bytes decoded on the receive side",
    ("dir", "codec"),
)
CODEC_SECONDS = REGISTRY.counter(
    "grit_codec_seconds_total",
    "Summed worker seconds spent in the PYTHON codec pool (sampling + "
    "compress, or decompress + CRC), by direction; the pool overlaps "
    "this with transport, so compare against wire/transfer seconds to "
    "see whether the codec hid inside the data path. The native file "
    "plane's drain does its codec work in C threads and reports bytes "
    "(grit_codec_bytes_total still counts) but not worker-seconds — "
    "its pacing evidence is grit_io_drain_seconds + the io.drain event",
    ("dir",),
)
CODEC_QUEUE_DEPTH = REGISTRY.gauge(
    "grit_codec_queue_depth",
    "Jobs queued (not yet picked up) in the shared codec worker pool at "
    "the most recent submission — sustained depth means the codec stage, "
    "not the transport, is the bottleneck of the dump/receive path",
)
IO_NATIVE_BYTES = REGISTRY.counter(
    "grit_io_native_bytes_total",
    "Raw payload bytes moved by the native file data plane "
    "(gritio-file), by plane: drain = dump-mirror chunks through the "
    "fused CRC+codec+O_DIRECT drain worker, place = restore container "
    "blocks decoded/verified natively, read = raw chunk ranges through "
    "the batched (io_uring/pread) read engine",
    ("plane",),  # drain | place | read
)
IO_READ_BATCHES = REGISTRY.counter(
    "grit_io_read_batches_total",
    "Batched-read calls of the native file plane by the engine that "
    "actually ran them — io_uring where the kernel has it, the "
    "concurrent-pread fallback otherwise; the ladder's bottom rung "
    "showing up on an io_uring kernel is a probe regression",
    ("engine",),  # io_uring | preadv
)
IO_DRAIN_SECONDS = REGISTRY.gauge(
    "grit_io_drain_seconds",
    "Wall seconds of the most recent dump's native mirror drain "
    "(first chunk enqueued through close) on this node — with "
    "grit_io_native_bytes_total{plane=drain} this is the dump_native "
    "throughput evidence",
)
IO_DEGRADE = REGISTRY.counter(
    "grit_io_degrade_total",
    "Legs that would have run the native file plane but fell back to "
    "the Python byte loops, by reason (disabled = GRIT_IO_NATIVE=0, "
    "unavailable = library missing/stale ABI, zstd = codec the native "
    "plane does not own, fault = injected io.* fault, error = a native "
    "call failed mid-leg) — paired with the io.degrade flight event; "
    "the degrade is never silent",
    ("reason",),
)
FLIGHT_EVENTS = REGISTRY.counter(
    "grit_flight_events_total",
    "Flight-recorder events emitted by this process, by phase family "
    "(the first dotted segment of the event name — a closed vocabulary "
    "from grit_tpu.obs.flight.EVENTS)",
    ("phase",),
)
CODEC_RATIO = REGISTRY.gauge(
    "grit_codec_ratio",
    "compressed/raw byte ratio of the most recent dump transport "
    "session (adaptive raw-shipped blocks count at 1.0), per direction "
    "of travel on this node",
)
WIRE_OVERLAP_FRACTION = REGISTRY.gauge(
    "grit_wire_overlap_fraction",
    "Fraction of the most recent wire session's bytes that reached the "
    "socket while the HBM dump was still draining (dump/send overlap)",
)
BLACKOUT_SECONDS = REGISTRY.gauge(
    "grit_last_blackout_seconds",
    "Duration of the most recent checkpoint blackout window "
    "(device quiesce through resume) on this node agent",
)
CHECKPOINTS_TOTAL = REGISTRY.counter(
    "grit_agent_checkpoints_total",
    "Pod checkpoints executed by this node agent",
    ("outcome",),
)
MIGRATION_ABORTS = REGISTRY.counter(
    "grit_migration_aborts_total",
    "Migration legs aborted back to a resumed source (driver=manager "
    "counts control-plane abort decisions; driver=agent counts node-side "
    "abort executions — one production abort increments both once)",
    ("driver",),
)
SOURCE_RESUME_SECONDS = REGISTRY.gauge(
    "grit_source_resume_seconds",
    "Wall seconds the most recent abort took from abort start until the "
    "source workload was unquiesced and resumable",
)
HEARTBEAT_AGE = REGISTRY.gauge(
    "grit_agent_heartbeat_age_seconds",
    "Age of the most recently observed agent-Job heartbeat lease, per CR "
    "kind (grit.dev/heartbeat annotation; Job creation time before the "
    "first renewal)",
    ("kind",),
)
AGENT_JOB_RETRIES = REGISTRY.counter(
    "grit_agent_job_retries_total",
    "Agent-Job re-creations scheduled by the manager watchdog, by CR "
    "kind and detection cause",
    ("kind", "cause"),
)

# -- gang slice migration (multi-host) ----------------------------------------

SLICE_BARRIER_SECONDS = REGISTRY.gauge(
    "grit_slice_barrier_seconds",
    "Wall seconds this host spent waiting at the most recent cross-host "
    "quiesce barrier after reaching the agreed cut step (the straggler "
    "wait — the slice quiesce scales with its max across hosts)",
)
SLICE_GANG_TOTAL = REGISTRY.counter(
    "grit_slice_gang_total",
    "Gang slice-migration outcomes recorded in the shared ledger "
    "(committed = every host's session verified and the commit record "
    "landed; aborted = some host's terminal failure drove the "
    "slice-wide abort)",
    ("outcome",),
)

# -- fleet migration scheduler (MigrationPlan) --------------------------------
#
# Plan-level observability: the wave's budgets and outcomes, fed by the
# plan controller every reconcile. Member-level numbers stay on the
# member CRs (status.progress) — these families answer the fleet
# questions: how many in flight, how deep the queue, how close to the
# declared ceilings, and how did the plan end.

FLEET_PLANS = REGISTRY.counter(
    "grit_fleet_plans_total",
    "MigrationPlans that reached a terminal verdict (Succeeded = every "
    "member migrated; PartiallyFailed = some member exhausted its "
    "plan-level retries after aborting back to source — per-pod "
    "reasons in status.pods[])",
    ("verdict",),
)
FLEET_MEMBERS = REGISTRY.counter(
    "grit_fleet_members_total",
    "Member migrations a plan resolved, by outcome: succeeded "
    "(terminal success phase), retried (terminal failure ridden back "
    "to source by the abort machine, fresh member CR created), failed "
    "(retries exhausted — recorded in status.pods[], plan verdict "
    "PartiallyFailed)",
    ("outcome",),
)
FLEET_PLACEMENTS = REGISTRY.counter(
    "grit_fleet_placements_total",
    "Bin-packing destination decisions, by outcome: placed, "
    "no_capacity (member stays Queued — capacity exhaustion never "
    "fails a pod), topology_mismatch, destination_rejected (unready "
    "node or armed fleet.place fault)",
    ("outcome",),
)
FLEET_QUEUE_PREEMPTIONS = REGISTRY.counter(
    "grit_fleet_queue_preemptions_total",
    "Queued admission slots a latency-critical member took ahead of an "
    "earlier-arrived batch member (queued slots only — in-flight "
    "migrations are never preempted)",
)
FLEET_CONCURRENT = REGISTRY.gauge(
    "grit_fleet_concurrent_migrations",
    "Member migrations in flight for the most recently reconciled "
    "MigrationPlan (the number its concurrency ceiling bounds; zeroed "
    "at the plan's terminal verdict)",
)
FLEET_QUEUE_DEPTH = REGISTRY.gauge(
    "grit_fleet_queue_depth",
    "Members waiting for an admission slot (budget or capacity), by "
    "priority class — a closed vocabulary from "
    "grit_tpu.api.types.PRIORITY_CLASSES",
    ("priority",),
)
FLEET_RATE_BPS = REGISTRY.gauge(
    "grit_fleet_rate_bps",
    "Summed live shipping rate (bytes/s) of every in-flight member "
    "migration, from the members' status.progress rateBps — the "
    "numerator of the fleet bandwidth utilization",
)
FLEET_BUDGET_UTILIZATION = REGISTRY.gauge(
    "grit_fleet_budget_utilization",
    "Utilization of the plan-declared budgets, per dimension: "
    "concurrency = in-flight / maxConcurrent; bandwidth = observed "
    "fleet rate / fleet budget (0 when unbudgeted)",
    ("dimension",),
)
FLEET_MAKESPAN_SECONDS = REGISTRY.gauge(
    "grit_fleet_last_makespan_seconds",
    "Wall seconds from the most recently finished plan's first member "
    "admission to its terminal verdict — the fleet makespan the bench "
    "trajectory gates",
)

# -- serving snapshot fan-out (RestoreSet) ------------------------------------
#
# The serving gauges/counters are fed from both ends of the fan-out: the
# serving agentlet's request-drain hook (device side) and the RestoreSet
# controller's clone fan-in (manager side).

SERVE_DRAIN_SECONDS = REGISTRY.gauge(
    "grit_serve_drain_seconds",
    "Wall seconds the most recent request-drain took between the "
    "quiesce request landing and the engine parking at its batch "
    "boundary — the serving workload's contribution to the blackout "
    "window (serialize mode: one batch boundary; drain mode: the "
    "run-to-completion tail)",
)
SERVE_DRAINED_SLOTS = REGISTRY.counter(
    "grit_serve_drained_slots_total",
    "In-flight slots resolved by request drains, by how: serialized "
    "(KV/position state shipped inside the snapshot) or drained "
    "(decoded to EOS/length before the park)",
    ("how",),
)
SERVE_CLONES = REGISTRY.counter(
    "grit_serve_clones_total",
    "Clone restore legs a RestoreSet resolved, by outcome: ready "
    "(Restore reached Restored), failed (terminal failure — recorded "
    "in status.replicas[], siblings unaffected), skipped (creation "
    "deferred by an armed serve.clone fault; retried next reconcile)",
    ("outcome",),
)
SERVE_READY_REPLICAS = REGISTRY.gauge(
    "grit_serve_ready_replicas",
    "readyReplicas of the most recently reconciled RestoreSet (the "
    "fan-out's readiness gate; zeroed when the set is deleted)",
)
SERVE_FANOUT_SECONDS = REGISTRY.gauge(
    "grit_serve_fanout_seconds",
    "Wall seconds from the most recently finished RestoreSet's first "
    "clone creation to its readyReplicas gate closing — the "
    "time-to-Nth-replica the serving bench trajectory gates",
)

# -- live migration telemetry plane (PR 8) ------------------------------------
#
# The progress gauges are fed by grit_tpu.obs.progress (byte accounting
# from the mirror/wire/transfer paths) and refreshed by the periodic
# sampler (grit_tpu.obs.sampler, GRIT_OBS_SAMPLE_S) so a scrape between
# events never reads a stale edge-triggered value. The histograms are
# per-operation latency distributions of the data-path hot legs — the
# shape (not just the sum) is what separates "slow link" from "stalls".

PROGRESS_BYTES_SHIPPED = REGISTRY.gauge(
    "grit_progress_bytes_shipped",
    "Bytes this migration leg has shipped so far (source: dump mirror + "
    "wire/upload; destination: frames received + staged), per role — "
    "the live numerator of the migration's progress/ETA",
    ("role",),
)
PROGRESS_TOTAL_BYTES = REGISTRY.gauge(
    "grit_progress_total_bytes",
    "Best current estimate of the bytes this migration leg must ship "
    "(0 until known), per role",
    ("role",),
)
PROGRESS_RATE_BPS = REGISTRY.gauge(
    "grit_progress_rate_bps",
    "Windowed shipping rate (bytes/s over the recent sample window) of "
    "this migration leg, per role",
    ("role",),
)
PROGRESS_ETA_SECONDS = REGISTRY.gauge(
    "grit_progress_eta_seconds",
    "Derived seconds until this leg finishes shipping at the current "
    "windowed rate (-1 when unknown: no total or zero rate), per role",
    ("role",),
)
PLACE_CHUNK_SECONDS = REGISTRY.histogram(
    "grit_place_chunk_seconds",
    "Per-array host-to-device place latency inside the restore pipeline "
    "(the top-priority blackout phase) — a fat tail here means device "
    "puts, not staging, bound the restore",
    (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
     60.0),
)
WIRE_FRAME_SEND_SECONDS = REGISTRY.histogram(
    "grit_wire_frame_send_seconds",
    "Per-frame socket write latency on the wire send workers; the "
    "distribution separates a uniformly slow link from intermittent "
    "receiver pushback",
    (0.0005, 0.002, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0),
)
WIRE_STALL_SECONDS = REGISTRY.histogram(
    "grit_wire_stall_seconds",
    "Duration of each producer stall on the bounded wire send queues "
    "(backpressure episodes, not their sum — grit_wire_seconds_total "
    "has that): many short stalls are healthy pacing, few long ones "
    "are a wedged consumer",
    (0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
)
STANDBY_FIRES = REGISTRY.counter(
    "grit_standby_fires_total",
    "Armed StandbyCheckpoints fired, by trigger: reclaim (preemption "
    "watcher saw a cloud reclaim taint / grit.dev/preempt), cordon (the "
    "drain controller's cordon path), operator (an explicit "
    "grit.dev/fire annotation forwarded without either watcher)",
    ("trigger",),
)
STANDBY_STALENESS_SECONDS = REGISTRY.gauge(
    "grit_standby_staleness_seconds",
    "Seconds since the armed standby's destination base was last "
    "flattened current (the quiesce cut of the last SHIPPED governed "
    "round): the state-loss bound a preemption at this instant would "
    "pay. Aged forward by the sampler between governor ticks",
)
STANDBY_DELTA_BACKLOG_BYTES = REGISTRY.gauge(
    "grit_standby_delta_backlog_bytes",
    "Dirty bytes the standby governor's last probe measured but chose "
    "not to ship (below the ship threshold, or dirty rate above link "
    "rate): the final-delta budget a fire right now would carry",
)
CODEC_WAIT_SECONDS = REGISTRY.histogram(
    "grit_codec_wait_seconds",
    "Per-block wait for a codec pool result on the dump/wire producer "
    "side — sustained mass in the high buckets means the codec pool, "
    "not the transport, is pacing the data path",
    (0.0005, 0.002, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0),
)


# -- hot-path profiling plane (PR 9) ------------------------------------------
#
# The per-role resource ledger (grit_tpu.obs.profile.sample_ledger,
# refreshed on the GRIT_OBS_SAMPLE_S sampler cadence) publishes this
# process's cumulative CPU/IO/RSS so "where did the blackout's CPU go"
# has a live numerator; the tick counter is the phase profiler's sample
# classification — the coverage evidence the CI obs lane gates on.

PROF_CPU_SECONDS = REGISTRY.gauge(
    "grit_prof_cpu_seconds",
    "Cumulative process CPU seconds from /proc/self/stat, by mode "
    "(user|system) — deltas over the sampler cadence give live cores in "
    "use per migration role",
    ("mode",),
)
PROF_IO_BYTES = REGISTRY.gauge(
    "grit_prof_io_bytes",
    "Cumulative bytes this process moved through the block layer "
    "(/proc/self/io read_bytes/write_bytes), by direction — the IO half "
    "of the per-role CPU/IO ledger",
    ("dir",),
)
PROF_RSS_BYTES = REGISTRY.gauge(
    "grit_prof_rss_bytes",
    "Resident set size of this process (VmRSS) at the last ledger "
    "sample",
)
PROF_CTX_SWITCHES = REGISTRY.gauge(
    "grit_prof_ctx_switches",
    "Cumulative context switches of this process, by kind (voluntary = "
    "blocking on IO/locks, involuntary = preempted while computing)",
    ("kind",),
)
PROF_CODEC_POOL_SATURATION = REGISTRY.gauge(
    "grit_prof_codec_pool_saturation",
    "(active + queued codec jobs) / pool workers at the last ledger "
    "sample — sustained >1 means the codec pool, not the transport, "
    "paces the dump/receive path",
)
PROF_SAMPLE_TICKS = REGISTRY.counter(
    "grit_prof_sample_ticks_total",
    "Thread samples taken by the phase-scoped profiler, by classified "
    "category (python/native/syscall/lock/idle/unknown — a closed "
    "vocabulary from grit_tpu.obs.profile.CATEGORIES)",
    ("category",),
)
PROF_TICK_SECONDS = REGISTRY.histogram(
    "grit_prof_tick_seconds",
    "Wall seconds one profiler tick spent sampling+classifying all "
    "threads — the profiler's own overhead, measured by the profiler "
    "(the <5% bench overhead gate's live counterpart)",
    (0.0001, 0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.5),
)


def render_threadz() -> str:
    """Stack dump of all live threads (the pprof-goroutine analogue;
    reference mounts pprof at app/manager.go:88-92)."""
    import sys
    import traceback

    frames = sys._current_frames()
    out = []
    for thread in threading.enumerate():
        frame = frames.get(thread.ident)
        out.append(f"--- thread {thread.name} (daemon={thread.daemon}) ---")
        if frame is not None:
            out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"
