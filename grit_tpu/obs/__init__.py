"""Observability: metrics registry + metrics/debug HTTP server +
live migration progress (tracker, sampler)."""

from grit_tpu.obs.metrics import (
    BLACKOUT_SECONDS,
    CHECKPOINTS_TOTAL,
    PHASE_TRANSITIONS,
    RECONCILE_ERRORS,
    REGISTRY,
    SNAPSHOT_BYTES,
    SNAPSHOT_SECONDS,
    TRANSFER_BYTES,
    TRANSFER_SECONDS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    render_threadz,
)
from grit_tpu.obs.server import (
    start_metrics_server,
    start_workload_metrics_server,
)

__all__ = [
    "BLACKOUT_SECONDS",
    "CHECKPOINTS_TOTAL",
    "PHASE_TRANSITIONS",
    "RECONCILE_ERRORS",
    "REGISTRY",
    "SNAPSHOT_BYTES",
    "SNAPSHOT_SECONDS",
    "TRANSFER_BYTES",
    "TRANSFER_SECONDS",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "render_threadz",
    "start_metrics_server",
    "start_workload_metrics_server",
]
