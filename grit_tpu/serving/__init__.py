"""Serving workload layer — request draining + snapshot fan-out.

This package is the inference half of the migration contract (ROADMAP
item 4). :mod:`grit_tpu.serving.adapter` generalizes the training
agentlet's quiesce hook into a *request-drain* hook for a
:class:`~grit_tpu.models.serving.ContinuousBatchingEngine`;
:mod:`grit_tpu.serving.fanout` drives N post-copy clone restores off
one verified snapshot — the device leg of the RestoreSet fan-out the
manager orchestrates (:mod:`grit_tpu.manager.restoreset_controller`).
"""

from grit_tpu.serving.adapter import (
    ServingAgentlet,
    ServingDrainTimeout,
    ServingDraining,
)
from grit_tpu.serving.fanout import CloneLeg, fan_out_clones

__all__ = [
    "ServingAgentlet",
    "ServingDrainTimeout",
    "ServingDraining",
    "CloneLeg",
    "fan_out_clones",
]
