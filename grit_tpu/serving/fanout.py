"""In-process snapshot fan-out: one staged snapshot → N clone engines.

The device leg of the RestoreSet story. The manager's controller fans a
verified snapshot out into N Restore CRs; each restore agent stages the
PVC/wire bytes onto its node exactly once — and every clone ENGINE on
that node restores from the SAME staged tree, so the source read pass
off the PVC is shared rather than multiplied by the replica count (the
transports' (size, mtime) skip semantics make a second agent leg
against an already-staged tree a no-op, and concurrent engine reads of
a committed tree are plain page-cache hits).

:func:`fan_out_clones` drives the engines' post-copy restores in
parallel threads: each clone's hot set places synchronously, the clone
starts serving new traffic immediately, and its cold KV tail lands
behind traffic (``serve.clone.*`` flight events mark the lifecycle —
including ``serve.clone.served``, the proof a replica answered before
its last byte arrived).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from grit_tpu.obs import flight


@dataclass
class CloneLeg:
    """One clone of the fan-out: its engine, its in-flight post-copy
    handle, and the evidence timestamps the bench/e2e read."""

    ordinal: int
    engine: object
    handle: object = None
    hot_placed_s: float = 0.0  # snapshot open → hot set on device
    first_token_s: float = 0.0  # snapshot open → first served token
    served_before_tail: bool = False
    error: BaseException | None = None
    _t0: float = field(default=0.0, repr=False)

    def serve_first(self, prompt, max_steps: int = 512) -> int:
        """Admit ``prompt`` into a free slot and decode its first token
        — the replica's first served request. Records whether the cold
        tail was still in flight when the token came back (the
        post-copy claim, measured not assumed)."""
        slot = self.engine.submit(prompt)
        deadline_steps = max_steps
        while deadline_steps > 0:
            emitted = self.engine.step()
            if slot in emitted:
                tail_in_flight = (self.handle is not None
                                  and not self.handle.done)
                self.first_token_s = time.monotonic() - self._t0
                self.served_before_tail = tail_in_flight
                flight.emit("serve.clone.served", ordinal=self.ordinal,
                            first_token_s=round(self.first_token_s, 4),
                            tail_in_flight=tail_in_flight)
                return emitted[slot]
            deadline_steps -= 1
        raise RuntimeError(f"clone {self.ordinal} never emitted a token")

    def finish(self, timeout: float | None = None) -> None:
        """Absorb the restored streams (blocks on the cold tail)."""
        self.engine.absorb_restored(timeout=timeout)


def fan_out_clones(directory: str, engines, *,
                   parallel: bool = True) -> list[CloneLeg]:
    """Start a post-copy restore of ``directory`` on every engine.

    Returns one :class:`CloneLeg` per engine with the hot set already
    placed (the handles' cold tails keep landing in the background).
    A clone whose restore raises carries the error on its leg instead
    of failing its siblings — all-or-nothing is the wrong contract for
    a fan-out whose point is independent replicas.
    """
    legs = [CloneLeg(ordinal=k, engine=e) for k, e in enumerate(engines)]

    def _one(leg: CloneLeg) -> None:
        leg._t0 = time.monotonic()
        flight.emit_near(directory, "serve.clone.start",
                         ordinal=leg.ordinal, clone=f"clone-{leg.ordinal}")
        try:
            leg.handle = leg.engine.restore_postcopy(directory)
            leg.hot_placed_s = time.monotonic() - leg._t0
        except BaseException as exc:  # noqa: BLE001 — sibling isolation
            leg.error = exc
            flight.emit_near(directory, "serve.clone.abort",
                             ordinal=leg.ordinal,
                             reason=f"{type(exc).__name__}: {exc}")

    if parallel:
        threads = [threading.Thread(target=_one, args=(leg,),
                                    name=f"grit-clone-{leg.ordinal}",
                                    daemon=True) for leg in legs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:
        for leg in legs:
            _one(leg)
    for leg in legs:
        if leg.error is None:
            flight.emit_near(directory, "serve.clone.ready",
                             ordinal=leg.ordinal,
                             hot_placed_s=round(leg.hot_placed_s, 4))
    return legs
