"""Serving agentlet adapter: the quiesce hook generalized to a
request-drain hook.

A training loop parks at "the next step boundary"; a serving engine has
no such single boundary — it has a *batch* boundary (between ragged
decode dispatches) and a policy question about the requests in flight
when the quiesce lands:

- ``serialize`` (default): park at the very next batch boundary. The
  in-flight slots' KV/position/RNG state ships INSIDE the snapshot (the
  continuous-batching state is one pytree), and the restored replica —
  or every clone of a fan-out — resumes the streams mid-token,
  bit-identically. Blackout contribution: one decode dispatch.
- ``drain``: stop admitting, keep decoding until every in-flight slot
  completes (EOS / length limit), then park an EMPTY grid. Bounded by
  ``GRIT_SERVE_DRAIN_TIMEOUT_S``; expiry raises
  :class:`ServingDrainTimeout` out of the serving loop — a drain that
  cannot finish must fail the migration attempt loudly, never silently
  serialize or park a half-drained batch.

The adapter owns an ordinary :class:`~grit_tpu.device.agentlet.Agentlet`
(same socket protocol, same node-agent addressing), so the managed
checkpoint flow needs no serving-specific control plane: the agent's
quiesce request simply takes the drain detour before the park, and the
dump reads the engine's **tagged** state
(:meth:`~grit_tpu.models.serving.ContinuousBatchingEngine.snapshot_state`)
so free-slot KV pages ship zero-elided.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from grit_tpu import faults
from grit_tpu.api import config
from grit_tpu.device.agentlet import Agentlet
from grit_tpu.obs import flight
from grit_tpu.obs.metrics import SERVE_DRAIN_SECONDS, SERVE_DRAINED_SLOTS

DRAIN_SERIALIZE = "serialize"
DRAIN_COMPLETE = "drain"


class ServingDrainTimeout(RuntimeError):
    """The 'drain' policy could not complete every in-flight request
    inside GRIT_SERVE_DRAIN_TIMEOUT_S. Deliberately loud: the operator
    chose run-to-completion semantics, and a silent fallback to
    serialization would change what the snapshot means."""


class ServingDraining(RuntimeError):
    """A submit raced an in-progress drain: admission is closed until
    the migration resumes the engine. Callers retry (or shed) — the
    request is not queued, because a quiesced engine cannot bound how
    long the queue would hold it."""


class ServingAgentlet:
    """Wraps a ContinuousBatchingEngine with the toggle endpoint.

    The serving loop decodes through :meth:`step`, calls
    :meth:`batch_boundary` once per decode round (the serving analogue
    of ``Agentlet.checkpoint_point``), and routes admissions through
    :meth:`submit` — the adapter serializes cross-thread submits
    against decode rounds and the drain. Everything else — socket,
    dump, resume, status — is the stock agentlet.

    Args:
      engine: the ContinuousBatchingEngine to serve.
      drain_mode: override for GRIT_SERVE_DRAIN_MODE.
      drain_timeout_s: override for GRIT_SERVE_DRAIN_TIMEOUT_S.
      emit_fn: optional ``(slot, token)`` callback for tokens decoded
        *during* a drain (drain mode finishes requests the caller's own
        step loop no longer sees — their tokens must not be lost).
      path: explicit agentlet socket path (tests).
    """

    def __init__(
        self,
        engine,
        *,
        drain_mode: str | None = None,
        drain_timeout_s: float | None = None,
        emit_fn: Callable[[int, int], None] | None = None,
        path: str | None = None,
    ) -> None:
        self.engine = engine
        mode = drain_mode or str(config.SERVE_DRAIN_MODE.get())
        if mode not in (DRAIN_SERIALIZE, DRAIN_COMPLETE):
            import logging  # noqa: PLC0415

            logging.getLogger(__name__).warning(
                "unknown %s=%r — degrading to %r",
                config.SERVE_DRAIN_MODE.name, mode, DRAIN_SERIALIZE)
            mode = DRAIN_SERIALIZE
        self.drain_mode = mode
        self.drain_timeout_s = (
            float(config.SERVE_DRAIN_TIMEOUT_S.get())
            if drain_timeout_s is None else float(drain_timeout_s))
        self.emit_fn = emit_fn
        self._rounds = 0  # batch boundaries crossed — the "step" counter
        self.last_drain = {}  # evidence of the most recent drain
        # Orders submit against the cutover: an admission holding this
        # lock completes BEFORE the drain starts (and ships in the
        # snapshot); one starting after the quiesce landed sees
        # `draining` and raises — closing the check-then-act window
        # between the draining test and engine.submit.
        self._admission = threading.Lock()
        self.agentlet = Agentlet(
            # The dump must ship the TAGGED state (free-slot KV pages
            # zeroed) so the codec's block elision sees them; the park's
            # device drain blocks on the RAW state — materializing (and
            # discarding) a full tagged KV copy per quiesce would double
            # the tag cost inside the blackout window. The drain policy
            # rides the agentlet's pre-park hook so it runs exactly once
            # per quiesce round, even when the request lands between the
            # serving loop's own pending check and the park.
            state_fn=engine.snapshot_state,
            quiesce_state_fn=lambda: engine.state,
            pre_park_fn=self._pre_park,
            step_fn=lambda: self._rounds,
            meta_fn=self._meta,
            path=path,
        )

    def _meta(self) -> dict:
        import numpy as np  # noqa: PLC0415

        return {
            "serving": True,
            "drain_mode": self.drain_mode,
            "active_slots": int(
                np.asarray(self.engine.state["active"]).sum()),
            # The engine's own snapshot metadata MUST ride the managed
            # dump too: without "submissions", a restored clone's first
            # admission would fold in an RNG stream id the source's
            # still-running slots already consumed (twinned sampling).
            **self.engine.snapshot_meta(),
        }

    # -- lifecycle --------------------------------------------------------------

    def start(self) -> "ServingAgentlet":
        self.agentlet.start()
        return self

    def stop(self) -> None:
        self.agentlet.stop()

    def __enter__(self) -> "ServingAgentlet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving loop hooks -----------------------------------------------------

    @property
    def draining(self) -> bool:
        """Admission is closed from the quiesce request until resume:
        while the drain runs AND while the engine sits parked (a prompt
        admitted into a parked engine would miss the snapshot — or, in
        drain mode, un-empty the grid the snapshot promised empty)."""
        return self.agentlet.quiesce_pending or self.agentlet.paused

    # grit: handoff(_admission)
    def submit(self, prompt) -> int:
        """Admission gate — see :attr:`draining`. Serialized against
        the drain AND against :meth:`step` via the admission lock: a
        submit that won the race finishes before the drain runs (and
        ships in the snapshot), and a cross-thread submit can never
        interleave its engine-state swap with a decode round's."""
        with self._admission:
            if self.draining:
                raise ServingDraining(
                    "engine is draining for a snapshot — retry after "
                    "resume")
            return self.engine.submit(prompt)

    # grit: loop-thread
    def step(self) -> dict[int, int]:
        """One decode round, serialized against cross-thread submits.
        The serving loop decodes through THIS (not ``engine.step()``
        directly): engine state updates are read-modify-write swaps of
        one pytree, so an unserialized submit racing a step would lose
        one side's write — an admitted slot that never decodes, or a
        whole round's position advance."""
        with self._admission:
            return self.engine.step()

    # grit: loop-thread
    def batch_boundary(self) -> None:
        """Call once per decode round. When a quiesce request is
        pending, the park runs the drain policy first (the agentlet's
        pre-park hook — atomic with the park decision, so a quiesce
        landing at any instant can never park an undrained grid)."""
        self._rounds += 1
        self.agentlet.checkpoint_point()

    # grit: loop-thread
    def _pre_park(self) -> None:
        # Barrier: any in-flight admission that read `draining` False
        # completes before the drain starts; everyone after sees the
        # pending quiesce and is refused.
        with self._admission:
            pass
        self._drain()

    # -- the drain itself -------------------------------------------------------

    # grit: loop-thread
    def _drain(self) -> None:
        import numpy as np  # noqa: PLC0415

        t0 = time.monotonic()
        if not getattr(self.engine, "resumed_all", True):
            # A clone still mid post-copy restore: settle the merge NOW
            # so the drain sees — and drain mode finishes — the migrated
            # streams too. Deferring to the dump-time absorb would
            # re-activate them into a grid the drain already declared
            # empty, shipping undrained slots under the drain contract.
            # The drain budget bounds the absorb as well: a stalled cold
            # tail must surface as the promised loud timeout, not block
            # the quiesce for the multi-minute stage timeout.
            try:
                self.engine.absorb_restored(
                    timeout=max(0.001, self.drain_timeout_s))
            except TimeoutError as exc:
                raise ServingDrainTimeout(
                    f"cold post-copy tail still landing after "
                    f"{self.drain_timeout_s:.0f}s "
                    f"({config.SERVE_DRAIN_TIMEOUT_S.name}): {exc}"
                ) from exc
        in_flight = int(np.asarray(self.engine.state["active"]).sum())
        flight.emit("serve.drain.start", mode=self.drain_mode,
                    slots=in_flight)
        ok = False
        drained_tokens = 0
        try:
            # Chaos seam: a raise here fails the drain — and with it the
            # quiesce attempt (the agent's request times out / errors) —
            # while the engine keeps serving. A hang models a wedged
            # drain the manager watchdog must catch by lease.
            faults.fault_point("serve.drain")
            if self.drain_mode == DRAIN_COMPLETE and in_flight:
                deadline = t0 + self.drain_timeout_s
                while True:
                    emitted = self.engine.step()
                    if not emitted:
                        break
                    drained_tokens += len(emitted)
                    if self.emit_fn is not None:
                        for slot, tok in emitted.items():
                            self.emit_fn(slot, tok)
                    if time.monotonic() > deadline:
                        raise ServingDrainTimeout(
                            f"drain still has "
                            f"{int(np.asarray(self.engine.state['active']).sum())} "
                            f"slots in flight after "
                            f"{self.drain_timeout_s:.0f}s "
                            f"({config.SERVE_DRAIN_TIMEOUT_S.name})")
                SERVE_DRAINED_SLOTS.inc(in_flight, how="drained")
            else:
                SERVE_DRAINED_SLOTS.inc(in_flight, how="serialized")
            ok = True
        finally:
            dt = time.monotonic() - t0
            SERVE_DRAIN_SECONDS.set(dt)
            self.last_drain = {
                "mode": self.drain_mode, "slots": in_flight,
                "drained_tokens": drained_tokens,
                "seconds": round(dt, 4), "ok": ok,
            }
            flight.emit("serve.drain.end", mode=self.drain_mode,
                        slots=in_flight, drained_tokens=drained_tokens,
                        ok=ok)
