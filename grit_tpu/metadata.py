"""Checkpoint-image layout: directory/file names shared by agent, shim and
interceptor.

Parity: reference ``pkg/metadata/metadata.go:7-10`` plus the checkpointctl
names it consumes (``CheckpointDirectory``, ``RootFsDiffTar`` — used at
``gritagent/checkpoint/runtime.go:124,131`` and ``runc/checkpoint_util.go:
22-28``). TPU additions: ``hbm/`` (device buffer dump) and
``device-state.json`` (topology + runtime version manifest) replace what the
CUDA path folds opaquely into CRIU ``pages-*.img``.

On-host layout for one pod checkpoint::

    <host-path>/<ns>/<ckpt-name>/
        download-state                  # sentinel: restore data fully staged
        <container-name>/
            checkpoint/                 # CRIU image dir (host process state)
            rootfs-diff.tar             # rw-layer diff
            container.log               # newest kubelet log file
            config.dump                 # container config (reference TODO,
            spec.dump                   #   runtime.go:145 — implemented here)
            hbm/                        # TPU: per-device HBM buffer files
            device-state.json           # TPU: topology/runtime manifest
"""

from __future__ import annotations

import json
import os

# Sentinel dropped by the restore agent when PVC→host download completes;
# polled by the CRI interceptor to hold PullImage (reference metadata.go:9,
# grit-interceptor.diff:140-172).
DOWNLOAD_STATE_FILE = "download-state"

# kubelet container log saved across migration (reference metadata.go:8).
CONTAINER_LOG_FILE = "container.log"

# checkpointctl-compatible names.
CHECKPOINT_DIRECTORY = "checkpoint"
ROOTFS_DIFF_TAR = "rootfs-diff.tar"
CONFIG_DUMP = "config.dump"
SPEC_DUMP = "spec.dump"

# TPU-native additions.
HBM_DIRECTORY = "hbm"
DEVICE_STATE_FILE = "device-state.json"

# Suffix for the in-progress work dir, atomically renamed on completion
# (reference gritagent/checkpoint/runtime.go:147-152).
WORK_SUFFIX = "-work"


def container_dir(ckpt_dir: str, container_name: str) -> str:
    return os.path.join(ckpt_dir, container_name)


def checkpoint_image_dir(ckpt_dir: str, container_name: str) -> str:
    return os.path.join(ckpt_dir, container_name, CHECKPOINT_DIRECTORY)


def sentinel_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, DOWNLOAD_STATE_FILE)


def write_device_state(path: str, manifest: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def read_device_state(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
