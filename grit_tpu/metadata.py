"""Checkpoint-image layout: directory/file names shared by agent, shim and
interceptor.

Parity: reference ``pkg/metadata/metadata.go:7-10`` plus the checkpointctl
names it consumes (``CheckpointDirectory``, ``RootFsDiffTar`` — used at
``gritagent/checkpoint/runtime.go:124,131`` and ``runc/checkpoint_util.go:
22-28``). TPU additions: ``hbm/`` (device buffer dump) and
``device-state.json`` (topology + runtime version manifest) replace what the
CUDA path folds opaquely into CRIU ``pages-*.img``.

On-host layout for one pod checkpoint::

    <host-path>/<ns>/<ckpt-name>/
        download-state                  # sentinel: restore data fully staged
        <container-name>/
            checkpoint/                 # CRIU image dir (host process state)
            rootfs-diff.tar             # rw-layer diff
            container.log               # newest kubelet log file
            config.dump                 # container config (reference TODO,
            spec.dump                   #   runtime.go:145 — implemented here)
            hbm/                        # TPU: per-device HBM buffer files
            device-state.json           # TPU: topology/runtime manifest
"""

from __future__ import annotations

import json
import os

# Sentinel dropped by the restore agent when PVC→host download completes;
# polled by the CRI interceptor to hold PullImage (reference metadata.go:9,
# grit-interceptor.diff:140-172).
DOWNLOAD_STATE_FILE = "download-state"

# kubelet container log saved across migration (reference metadata.go:8).
CONTAINER_LOG_FILE = "container.log"

# checkpointctl-compatible names.
CHECKPOINT_DIRECTORY = "checkpoint"
ROOTFS_DIFF_TAR = "rootfs-diff.tar"
CONFIG_DUMP = "config.dump"
SPEC_DUMP = "spec.dump"

# TPU-native additions.
HBM_DIRECTORY = "hbm"
DEVICE_STATE_FILE = "device-state.json"

# Suffix for the in-progress work dir, atomically renamed on completion
# (reference gritagent/checkpoint/runtime.go:147-152).
WORK_SUFFIX = "-work"

# Streamed-staging journal, dropped at the staging destination root by the
# restore agent's chunk-streamed transfer (grit_tpu.agent.copy.StageJournal)
# and polled by the device-side restore pipeline
# (grit_tpu.device.snapshot._StageMonitor): one JSON line per completed
# file / per-file contiguous-byte waterline advance, with a terminal
# ``{"complete": true}`` or ``{"failed": msg}`` line. This is what lets the
# restore begin placing arrays while later chunks are still in flight from
# the PVC.
STAGE_JOURNAL_FILE = ".grit-stage-journal"

# First line of every snapshot COMMIT sentinel (grit_tpu.device.snapshot
# writes it; the jax-free agent layer verifies mirror COMMITs against it
# without importing the device module).
SNAPSHOT_FORMAT = "grit-tpu-snapshot-v1"

# Wire-mode migration (GRIT_MIGRATION_PATH=wire): the destination agent's
# WireReceiver publishes its listen endpoint here, inside the checkpoint's
# PVC work dir — the only rendezvous both agents already share — and the
# source agent polls for it before dumping. Removed when the wire session
# ends (either way), so a later attempt never dials a dead listener.
WIRE_ENDPOINT_FILE = ".grit-wire-endpoint.json"

# Dropped by the source agent (wire mode only) once the asynchronous PVC
# durability tee holds the complete checkpoint tree: the destination's
# loud wire→PVC fallback gates its re-stage on this instead of racing a
# mid-flight upload.
PVC_TEE_COMPLETE_FILE = ".grit-pvc-tee-complete"

# Per-migration flight-recorder log (grit_tpu.obs.flight): one JSONL
# phase-boundary event per line, appended crash-safe by every process on
# the migration path, next to the termination-reason file in the agent
# work/stage dir. Node-local observability: excluded from every transfer
# and wire tree walk, never shipped with the checkpoint.
FLIGHT_LOG_FILE = ".grit-flight.jsonl"

# Per-migration live-progress snapshot (grit_tpu.obs.progress): one JSON
# object, atomically replaced on the lease/sampler cadence, next to the
# flight log in the agent work/stage dir. `gritscope watch` tails it for
# the live bytes/rate/ETA line. Node-local observability like the flight
# log: excluded from every transfer and wire tree walk (it changes WHILE
# transfers run — shipping it would tear wire commit size maps).
PROGRESS_FILE = ".grit-progress.json"

# Per-phase profiler artifacts (grit_tpu.obs.profile): collapsed-stack
# samples of one flight-bracketed phase, written as
# ``.grit-prof-<phase>.folded`` next to the flight log when the phase
# closes. Node-local observability like the flight log and the progress
# snapshot: excluded from every transfer and wire tree walk (they appear
# mid-migration, exactly when a tree walk would capture a file the
# commit size map has never seen).
PROF_FILE_PREFIX = ".grit-prof-"

# Standby fire signal (grit_tpu.agent.standby): dropping this file into
# the armed agent's work dir (or the shared PVC work dir) fires the
# standby — its content is the fire reason. The no-apiserver twin of the
# grit.dev/fire Job annotation. Node-local control state like the flight
# log: excluded from every transfer and wire tree walk (it appears at
# fire time, mid-walk, and must never ship with the checkpoint).
FIRE_FILE = ".grit-fire"

# Fleet migration scheduler (grit_tpu.manager.fleet): the plan
# controller atomically publishes one snapshot per MigrationPlan —
# member states + folded per-member progress + budget utilization —
# into GRIT_FLEET_STATUS_DIR as
# ``.grit-fleet-<namespace>-<plan>.json``; `gritscope watch --plan`
# tails it for the live fleet view. Manager-side observability (never
# written into checkpoint trees, so no transfer-walk exclusion needed).
FLEET_STATUS_FILE_PREFIX = ".grit-fleet-"
FLEET_STATUS_FILE_SUFFIX = ".json"


def fleet_status_filename(namespace: str, plan: str) -> str:
    return f"{FLEET_STATUS_FILE_PREFIX}{namespace}-{plan}" \
           f"{FLEET_STATUS_FILE_SUFFIX}"


# Serving snapshot fan-out (grit_tpu.manager.restoreset_controller): the
# RestoreSet controller atomically publishes one snapshot per set —
# per-clone states + folded per-clone restore progress — into
# GRIT_SERVE_STATUS_DIR as ``.grit-restoreset-<namespace>-<name>.json``;
# `gritscope watch --restoreset` tails it for the live fan-out view.
# Manager-side observability like the fleet snapshot (never written into
# checkpoint trees, so no transfer-walk exclusion needed).
RESTORESET_STATUS_FILE_PREFIX = ".grit-restoreset-"
RESTORESET_STATUS_FILE_SUFFIX = ".json"


def restoreset_status_filename(namespace: str, name: str) -> str:
    return f"{RESTORESET_STATUS_FILE_PREFIX}{namespace}-{name}" \
           f"{RESTORESET_STATUS_FILE_SUFFIX}"


# Gang slice migration ledger (grit_tpu.agent.slicerole): a directory of
# per-host marker files + the COMMIT/ABORT records in the SHARED PVC
# work dir, through which the N per-host agent legs of one slice
# migration agree on the all-or-nothing outcome (every destination
# parks "prepared" until the commit record lands; any host's failure
# writes ABORT for all). Coordination state, not checkpoint data:
# excluded — as a whole directory — from every transfer and wire tree
# walk (markers appear WHILE transfers run, and shipping them would
# both tear commit size maps and replay a stale gang outcome into the
# next attempt's ledger).
SLICE_LEDGER_DIRNAME = ".grit-slice"


def container_dir(ckpt_dir: str, container_name: str) -> str:
    return os.path.join(ckpt_dir, container_name)


def checkpoint_image_dir(ckpt_dir: str, container_name: str) -> str:
    return os.path.join(ckpt_dir, container_name, CHECKPOINT_DIRECTORY)


def sentinel_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, DOWNLOAD_STATE_FILE)


# grit: atomic-commit
def atomic_write_text(path: str, data: str) -> None:
    """Crash-atomic small-file write: tmp + fsync + rename. The one
    sanctioned way to flip a durable artifact (manifest, sentinel,
    status snapshot, marker) — a reader can observe the old content or
    the new content, never a torn or empty file, even across power
    loss. The tmp name is pid-qualified so concurrent writers of the
    same artifact can never tear each other's staging file."""
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# grit: atomic-commit
def atomic_write_json(path: str, obj, **dump_kw) -> None:
    """:func:`atomic_write_text` for the JSON artifacts (manifests,
    fleet/restoreset status snapshots, ledger markers)."""
    atomic_write_text(path, json.dumps(obj, **dump_kw))


def write_device_state(path: str, manifest: dict) -> None:
    atomic_write_json(path, manifest, indent=2, sort_keys=True)


def read_device_state(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def stage_timeout_s() -> float:
    """GRIT_TPU_STAGE_TIMEOUT_S (default 900): how long any consumer of
    staged-in-flight data (restore pipeline chunk gates, wire eof/commit
    verification) waits for bytes that never arrive before failing loud.
    One policy, shared by the device layer and the jax-free agent layer.
    (The malformed-value-degrades-to-default policy the old env_float
    helper carried now lives in the config registry itself.)"""
    from grit_tpu.api import config  # noqa: PLC0415 — keep metadata jax-free-light

    return config.TPU_STAGE_TIMEOUT_S.get()


def crc32_file(path: str) -> int:
    """Whole-file crc32 in bounded windows (small metadata files only —
    data files are verified via :func:`chunk_stream_signature` so nobody
    re-reads the multi-GB payload)."""
    import zlib  # noqa: PLC0415 — keep module import surface stdlib-tiny

    h = 0
    with open(path, "rb") as f:
        while buf := f.read(1 << 20):
            h = zlib.crc32(buf, h)
    return h & 0xFFFFFFFF


def chunk_stream_signature(chunks) -> int:
    """Order-sensitive signature of a snapshot data file's chunk stream.

    Folds each chunk's ``(crc, nbytes)`` — both already computed at dump
    time — into one crc32. Both ends of the streaming-mirror protocol can
    derive it from metadata alone (the dump side from the chunks it
    appended, the upload-skip side from ``MANIFEST.json``), so verifying
    "mirror bytes == source bytes" never re-reads the multi-GB payload.
    ``chunks``: iterable of ``(crc, nbytes)`` pairs in file-offset order.
    """
    import zlib  # noqa: PLC0415 — keep module import surface stdlib-tiny

    sig = 0
    for crc, nbytes in chunks:
        sig = zlib.crc32(f"{crc}:{nbytes};".encode(), sig)
    return sig & 0xFFFFFFFF


def manifest_data_file_signature(manifest: dict, filename: str) -> int:
    """:func:`chunk_stream_signature` recomputed from a parsed snapshot
    ``MANIFEST.json`` dict for one physical data file. Reference chunks
    (``ref_dir``) are excluded — they hold no bytes in this snapshot."""
    pairs = []
    for rec in manifest.get("arrays", []):
        for c in rec.get("chunks", []):
            if c.get("file") == filename and not c.get("ref_dir"):
                pairs.append(
                    (c["offset"], c.get("crc", c.get("crc32")), c["nbytes"])
                )
    pairs.sort(key=lambda t: t[0])
    return chunk_stream_signature((crc, n) for _, crc, n in pairs)
