"""Typed Kubernetes-shaped objects (the subset GRIT's control plane touches).

These mirror the k8s core/batch types the reference consumes via client-go:
Pod/Job/Node/PVC/Secret/ConfigMap plus metav1 ObjectMeta/OwnerReference/
Condition. Only fields the control plane actually reads/writes are modeled.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Any


@dataclass
class OwnerReference:
    """metav1.OwnerReference — identity matching for restore-pod selection
    uses UID equality of the *controller* ownerRef
    (reference pod_restore_default.go:70-91)."""

    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner_references: list[OwnerReference] = field(default_factory=list)
    resource_version: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None

    def controller_ref(self) -> OwnerReference | None:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None


@dataclass
class Condition:
    """metav1.Condition. The controllers append one condition per phase
    transition with the phase name as type (reference util.go:173-214)."""

    type: str = ""
    status: str = "True"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0
    observed_generation: int = 0


@dataclass
class LabelSelector:
    match_labels: dict[str, str] = field(default_factory=dict)

    def matches(self, labels: dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.match_labels.items())


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False


@dataclass
class Volume:
    """Union-ish volume: exactly one of host_path / pvc_claim_name /
    projected_kind is set (only the shapes the agent job + hash care about)."""

    name: str = ""
    host_path: str | None = None
    pvc_claim_name: str | None = None
    projected_kind: str | None = None  # e.g. "kube-api-access"


@dataclass
class ResourceRequirements:
    # e.g. {"google.com/tpu": 8} — TPU chips requested by the workload.
    limits: dict[str, Any] = field(default_factory=dict)
    requests: dict[str, Any] = field(default_factory=dict)


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    env: list[EnvVar] = field(default_factory=list)
    volume_mounts: list[VolumeMount] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    volumes: list[Volume] = field(default_factory=list)
    node_name: str = ""
    host_network: bool = False
    restart_policy: str = "Always"
    runtime_class_name: str | None = None
    node_selector: dict[str, str] = field(default_factory=dict)


@dataclass
class ContainerStatus:
    name: str = ""
    ready: bool = False
    container_id: str = ""  # "containerd://<id>"


@dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    conditions: list[Condition] = field(default_factory=list)
    container_statuses: list[ContainerStatus] = field(default_factory=list)
    host_ip: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class JobSpec:
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    backoff_limit: int = 3
    ttl_seconds_after_finished: int | None = None


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    conditions: list[Condition] = field(default_factory=list)

    def complete(self) -> bool:
        return any(c.type == "Complete" and c.status == "True" for c in self.conditions)

    def is_failed(self) -> bool:
        return any(c.type == "Failed" and c.status == "True" for c in self.conditions)


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    kind = "Job"


@dataclass
class NodeStatus:
    # Ready condition is what the checkpoint webhook checks
    # (reference checkpoint_webhook.go:55-63).
    conditions: list[Condition] = field(default_factory=list)
    # TPU topology advertised by the node (GKE tpu-topology label analogue),
    # used by restore-side scheduling checks.
    allocatable: dict[str, Any] = field(default_factory=dict)

    def ready(self) -> bool:
        return any(c.type == "Ready" and c.status == "True" for c in self.conditions)


@dataclass
class Taint:
    """core/v1 Taint (key/value/effect only — what the preemption
    watcher reads; GKE stamps a reclaim-notice taint on spot VMs
    seconds before termination)."""

    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class NodeSpec:
    # kubectl cordon / the drain flow set this; the drain controller
    # watches for the False→True transition.
    unschedulable: bool = False
    # Reclaim/termination notices arrive as taints (GKE spot:
    # cloud.google.com/impending-node-termination); the preemption
    # watcher fires armed StandbyCheckpoints on them.
    taints: list[Taint] = field(default_factory=list)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"


@dataclass
class PVCStatus:
    phase: str = "Pending"  # Pending | Bound | Lost


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: PVCStatus = field(default_factory=PVCStatus)

    kind = "PersistentVolumeClaim"


@dataclass
class Secret:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, bytes] = field(default_factory=dict)

    kind = "Secret"


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)

    kind = "ConfigMap"


@dataclass
class Event:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    reason: str = ""
    message: str = ""
    type: str = "Normal"

    kind = "Event"


@dataclass
class WebhookConfiguration:
    """Stand-in for Validating/MutatingWebhookConfiguration — the secret/cert
    controller patches ca_bundle into these (reference
    secret_controller.go:186-234)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    webhook_type: str = "Validating"  # "Validating" | "Mutating"
    ca_bundle: bytes = b""

    kind = "WebhookConfiguration"


def deep_copy(obj: Any) -> Any:
    """DeepCopy analogue; the in-process API stores/returns copies so callers
    can't mutate server state behind the API's back."""

    return copy.deepcopy(obj)


def now() -> float:
    return time.time()
