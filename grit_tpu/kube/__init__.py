"""Minimal in-process Kubernetes object model + API server.

The reference control plane is built on controller-runtime against a real
kube-apiserver. This build keeps the same architecture (typed objects,
controllers with workqueues, admission webhooks, watches) but runs it against
an in-process API (:class:`grit_tpu.kube.cluster.Cluster`) so the entire
control plane is unit-testable without a cluster — the envtest inversion
demanded by SURVEY §4. A real-cluster adapter can implement the same
:class:`ClusterAPI` surface.
"""

from grit_tpu.kube.objects import (  # noqa: F401
    Condition,
    ConfigMap,
    Container,
    Event,
    Job,
    JobSpec,
    JobStatus,
    LabelSelector,
    Node,
    ObjectMeta,
    OwnerReference,
    PersistentVolumeClaim,
    Pod,
    PodSpec,
    PodStatus,
    Secret,
    Volume,
    VolumeMount,
)
from grit_tpu.kube.cluster import AdmissionDenied, Cluster, Conflict, NotFound  # noqa: F401
