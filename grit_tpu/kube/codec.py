"""Typed object ↔ Kubernetes JSON codec for the real-apiserver adapter.

The control plane reconciles :mod:`grit_tpu.kube.objects` dataclasses; this
module maps them onto the wire representation the kube-apiserver speaks
(camelCase JSON, RFC3339 timestamps, base64 Secret data, GVK-specific REST
paths). Decoded objects carry their raw JSON in ``obj._raw`` so writes can
round-trip fields the typed model does not cover (a PUT built only from the
modeled fields would silently wipe them).

Parity: the role client-go's typed clientset + scheme play for the reference
manager (``cmd/grit-manager/app/manager.go:75-189``).
"""

from __future__ import annotations

import base64
import calendar
import copy
import time
from dataclasses import dataclass
from typing import Any, Callable

from grit_tpu.api.constants import API_GROUP as GROUP, API_VERSION as VERSION
from grit_tpu.api.types import (
    Checkpoint,
    CheckpointPhase,
    CheckpointSpec,
    CheckpointStatus,
    MigrationPlan,
    MigrationPlanBudget,
    MigrationPlanDestination,
    MigrationPlanMember,
    MigrationPlanPhase,
    MigrationPlanSpec,
    MigrationPlanStatus,
    Restore,
    RestorePhase,
    RestoreSet,
    RestoreSetPhase,
    RestoreSetSpec,
    RestoreSetStatus,
    RestoreSetTemplate,
    RestoreSpec,
    RestoreStatus,
    VolumeClaimSource,
)
from grit_tpu.kube import objects as k8s


# -- scalar helpers -----------------------------------------------------------


def _to_rfc3339(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def _from_rfc3339(s: str | None) -> float:
    if not s:
        return 0.0
    try:
        return float(calendar.timegm(time.strptime(s[:19], "%Y-%m-%dT%H:%M:%S")))
    except ValueError:
        return 0.0


def _rv_int(rv: Any) -> int:
    try:
        return int(rv)
    except (TypeError, ValueError):
        return 0


# -- metadata -----------------------------------------------------------------


def decode_meta(raw: dict) -> k8s.ObjectMeta:
    m = raw.get("metadata", {}) or {}
    return k8s.ObjectMeta(
        name=m.get("name", ""),
        namespace=m.get("namespace", "default"),
        uid=m.get("uid", ""),
        labels=dict(m.get("labels") or {}),
        annotations=dict(m.get("annotations") or {}),
        owner_references=[
            k8s.OwnerReference(
                api_version=r.get("apiVersion", ""),
                kind=r.get("kind", ""),
                name=r.get("name", ""),
                uid=r.get("uid", ""),
                controller=bool(r.get("controller")),
            )
            for r in (m.get("ownerReferences") or [])
        ],
        resource_version=_rv_int(m.get("resourceVersion")),
        creation_timestamp=_from_rfc3339(m.get("creationTimestamp")),
        deletion_timestamp=(
            _from_rfc3339(m["deletionTimestamp"])
            if m.get("deletionTimestamp")
            else None
        ),
    )


def encode_meta(meta: k8s.ObjectMeta, raw_meta: dict | None = None) -> dict:
    m = copy.deepcopy(raw_meta) if raw_meta else {}
    m["name"] = meta.name
    if meta.namespace:
        m["namespace"] = meta.namespace
    if meta.labels:
        m["labels"] = dict(meta.labels)
    elif "labels" in m:
        del m["labels"]
    if meta.annotations:
        m["annotations"] = dict(meta.annotations)
    elif "annotations" in m:
        del m["annotations"]
    if meta.owner_references:
        m["ownerReferences"] = [
            {
                "apiVersion": r.api_version,
                "kind": r.kind,
                "name": r.name,
                "uid": r.uid,
                "controller": r.controller,
            }
            for r in meta.owner_references
        ]
    return m


def _decode_conditions(raw: list | None) -> list[k8s.Condition]:
    return [
        k8s.Condition(
            type=c.get("type", ""),
            status=c.get("status", "True"),
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_transition_time=_from_rfc3339(c.get("lastTransitionTime")),
            observed_generation=c.get("observedGeneration", 0),
        )
        for c in (raw or [])
    ]


def _encode_conditions(conds: list[k8s.Condition]) -> list[dict]:
    return [
        {
            "type": c.type,
            "status": c.status,
            "reason": c.reason,
            "message": c.message,
            "lastTransitionTime": _to_rfc3339(c.last_transition_time or time.time()),
            "observedGeneration": c.observed_generation,
        }
        for c in conds
    ]


# -- pod / job ----------------------------------------------------------------


def _decode_container(raw: dict) -> k8s.Container:
    res = raw.get("resources") or {}
    return k8s.Container(
        name=raw.get("name", ""),
        image=raw.get("image", ""),
        command=list(raw.get("command") or []),
        args=list(raw.get("args") or []),
        env=[
            k8s.EnvVar(name=e.get("name", ""), value=e.get("value", ""))
            for e in (raw.get("env") or [])
        ],
        volume_mounts=[
            k8s.VolumeMount(
                name=v.get("name", ""),
                mount_path=v.get("mountPath", ""),
                read_only=bool(v.get("readOnly")),
            )
            for v in (raw.get("volumeMounts") or [])
        ],
        resources=k8s.ResourceRequirements(
            limits=dict(res.get("limits") or {}),
            requests=dict(res.get("requests") or {}),
        ),
    )


def _encode_container(c: k8s.Container) -> dict:
    out: dict = {"name": c.name, "image": c.image}
    if c.command:
        out["command"] = list(c.command)
    if c.args:
        out["args"] = list(c.args)
    if c.env:
        out["env"] = [{"name": e.name, "value": e.value} for e in c.env]
    if c.volume_mounts:
        out["volumeMounts"] = [
            {"name": v.name, "mountPath": v.mount_path, "readOnly": v.read_only}
            for v in c.volume_mounts
        ]
    if c.resources.limits or c.resources.requests:
        out["resources"] = {}
        if c.resources.limits:
            out["resources"]["limits"] = dict(c.resources.limits)
        if c.resources.requests:
            out["resources"]["requests"] = dict(c.resources.requests)
    return out


def _decode_volume(raw: dict) -> k8s.Volume:
    v = k8s.Volume(name=raw.get("name", ""))
    if "hostPath" in raw:
        v.host_path = raw["hostPath"].get("path", "")
    elif "persistentVolumeClaim" in raw:
        v.pvc_claim_name = raw["persistentVolumeClaim"].get("claimName", "")
    elif "projected" in raw:
        v.projected_kind = "kube-api-access"
    return v


def _encode_volume(v: k8s.Volume) -> dict:
    out: dict = {"name": v.name}
    if v.host_path is not None:
        out["hostPath"] = {"path": v.host_path}
    elif v.pvc_claim_name is not None:
        out["persistentVolumeClaim"] = {"claimName": v.pvc_claim_name}
    elif v.projected_kind is not None:
        out["projected"] = {"sources": []}
    return out


def _decode_pod_spec(raw: dict) -> k8s.PodSpec:
    return k8s.PodSpec(
        containers=[_decode_container(c) for c in (raw.get("containers") or [])],
        volumes=[_decode_volume(v) for v in (raw.get("volumes") or [])],
        node_name=raw.get("nodeName", ""),
        host_network=bool(raw.get("hostNetwork")),
        restart_policy=raw.get("restartPolicy", "Always"),
        runtime_class_name=raw.get("runtimeClassName"),
        node_selector=dict(raw.get("nodeSelector") or {}),
    )


def _encode_pod_spec(s: k8s.PodSpec) -> dict:
    out: dict = {
        "containers": [_encode_container(c) for c in s.containers],
    }
    if s.volumes:
        out["volumes"] = [_encode_volume(v) for v in s.volumes]
    if s.node_name:
        out["nodeName"] = s.node_name
    if s.host_network:
        out["hostNetwork"] = True
    if s.restart_policy != "Always":
        out["restartPolicy"] = s.restart_policy
    if s.runtime_class_name:
        out["runtimeClassName"] = s.runtime_class_name
    if s.node_selector:
        out["nodeSelector"] = dict(s.node_selector)
    return out


def decode_pod(raw: dict) -> k8s.Pod:
    st = raw.get("status") or {}
    pod = k8s.Pod(
        metadata=decode_meta(raw),
        spec=_decode_pod_spec(raw.get("spec") or {}),
        status=k8s.PodStatus(
            phase=st.get("phase", "Pending"),
            conditions=_decode_conditions(st.get("conditions")),
            container_statuses=[
                k8s.ContainerStatus(
                    name=c.get("name", ""),
                    ready=bool(c.get("ready")),
                    container_id=c.get("containerID", ""),
                )
                for c in (st.get("containerStatuses") or [])
            ],
            host_ip=st.get("hostIP", ""),
        ),
    )
    pod._raw = raw  # type: ignore[attr-defined]
    return pod


def encode_pod(pod: k8s.Pod) -> dict:
    raw = copy.deepcopy(getattr(pod, "_raw", None) or {})
    raw["apiVersion"] = "v1"
    raw["kind"] = "Pod"
    raw["metadata"] = encode_meta(pod.metadata, raw.get("metadata"))
    raw["spec"] = {**(raw.get("spec") or {}), **_encode_pod_spec(pod.spec)}
    status = {**(raw.get("status") or {}), "phase": pod.status.phase}
    if pod.status.conditions:
        status["conditions"] = _encode_conditions(pod.status.conditions)
    if pod.status.container_statuses:
        status["containerStatuses"] = [
            {"name": c.name, "ready": c.ready, "containerID": c.container_id}
            for c in pod.status.container_statuses
        ]
    if pod.status.host_ip:
        status["hostIP"] = pod.status.host_ip
    raw["status"] = status
    return raw


def decode_job(raw: dict) -> k8s.Job:
    st = raw.get("status") or {}
    tmpl = ((raw.get("spec") or {}).get("template")) or {}
    job = k8s.Job(
        metadata=decode_meta(raw),
        spec=k8s.JobSpec(
            template=k8s.PodTemplateSpec(
                metadata=decode_meta(tmpl),
                spec=_decode_pod_spec(tmpl.get("spec") or {}),
            ),
            backoff_limit=(raw.get("spec") or {}).get("backoffLimit", 3),
            ttl_seconds_after_finished=(raw.get("spec") or {}).get(
                "ttlSecondsAfterFinished"
            ),
        ),
        status=k8s.JobStatus(
            active=st.get("active", 0),
            succeeded=st.get("succeeded", 0),
            failed=st.get("failed", 0),
            conditions=_decode_conditions(st.get("conditions")),
        ),
    )
    job._raw = raw  # type: ignore[attr-defined]
    return job


def encode_job(job: k8s.Job) -> dict:
    raw = copy.deepcopy(getattr(job, "_raw", None) or {})
    raw["apiVersion"] = "batch/v1"
    raw["kind"] = "Job"
    raw["metadata"] = encode_meta(job.metadata, raw.get("metadata"))
    spec = raw.get("spec") or {}
    spec["backoffLimit"] = job.spec.backoff_limit
    if job.spec.ttl_seconds_after_finished is not None:
        spec["ttlSecondsAfterFinished"] = job.spec.ttl_seconds_after_finished
    tmpl = spec.get("template") or {}
    tmpl["metadata"] = encode_meta(
        job.spec.template.metadata, tmpl.get("metadata")
    )
    tmpl["spec"] = {
        **(tmpl.get("spec") or {}),
        **_encode_pod_spec(job.spec.template.spec),
    }
    spec["template"] = tmpl
    raw["spec"] = spec
    status = {
        **(raw.get("status") or {}),
        "active": job.status.active,
        "succeeded": job.status.succeeded,
        "failed": job.status.failed,
    }
    if job.status.conditions:
        status["conditions"] = _encode_conditions(job.status.conditions)
    raw["status"] = status
    return raw


# -- node / pvc / secret / configmap / event ---------------------------------


def decode_node(raw: dict) -> k8s.Node:
    st = raw.get("status") or {}
    sp = raw.get("spec") or {}
    node = k8s.Node(
        metadata=decode_meta(raw),
        spec=k8s.NodeSpec(unschedulable=bool(sp.get("unschedulable"))),
        status=k8s.NodeStatus(
            conditions=_decode_conditions(st.get("conditions")),
            allocatable=dict(st.get("allocatable") or {}),
        ),
    )
    node.metadata.namespace = ""  # cluster-scoped
    node._raw = raw  # type: ignore[attr-defined]
    return node


def encode_node(node: k8s.Node) -> dict:
    raw = copy.deepcopy(getattr(node, "_raw", None) or {})
    raw["apiVersion"] = "v1"
    raw["kind"] = "Node"
    raw["metadata"] = encode_meta(node.metadata, raw.get("metadata"))
    raw["metadata"].pop("namespace", None)
    spec = dict(raw.get("spec") or {})
    if node.spec.unschedulable:
        spec["unschedulable"] = True
    else:
        spec.pop("unschedulable", None)
    raw["spec"] = spec
    status = dict(raw.get("status") or {})
    if node.status.conditions:
        status["conditions"] = _encode_conditions(node.status.conditions)
    if node.status.allocatable:
        status["allocatable"] = dict(node.status.allocatable)
    raw["status"] = status
    return raw


def decode_pvc(raw: dict) -> k8s.PersistentVolumeClaim:
    pvc = k8s.PersistentVolumeClaim(
        metadata=decode_meta(raw),
        status=k8s.PVCStatus(phase=(raw.get("status") or {}).get("phase", "Pending")),
    )
    pvc._raw = raw  # type: ignore[attr-defined]
    return pvc


def encode_pvc(pvc: k8s.PersistentVolumeClaim) -> dict:
    raw = copy.deepcopy(getattr(pvc, "_raw", None) or {})
    raw["apiVersion"] = "v1"
    raw["kind"] = "PersistentVolumeClaim"
    raw["metadata"] = encode_meta(pvc.metadata, raw.get("metadata"))
    raw["status"] = {**(raw.get("status") or {}), "phase": pvc.status.phase}
    return raw


def decode_secret(raw: dict) -> k8s.Secret:
    sec = k8s.Secret(
        metadata=decode_meta(raw),
        data={
            k: base64.b64decode(v) for k, v in (raw.get("data") or {}).items()
        },
    )
    sec._raw = raw  # type: ignore[attr-defined]
    return sec


def encode_secret(sec: k8s.Secret) -> dict:
    raw = copy.deepcopy(getattr(sec, "_raw", None) or {})
    raw["apiVersion"] = "v1"
    raw["kind"] = "Secret"
    raw["metadata"] = encode_meta(sec.metadata, raw.get("metadata"))
    raw["data"] = {
        k: base64.b64encode(v).decode() for k, v in sec.data.items()
    }
    return raw


def decode_configmap(raw: dict) -> k8s.ConfigMap:
    cm = k8s.ConfigMap(
        metadata=decode_meta(raw), data=dict(raw.get("data") or {})
    )
    cm._raw = raw  # type: ignore[attr-defined]
    return cm


def encode_configmap(cm: k8s.ConfigMap) -> dict:
    raw = copy.deepcopy(getattr(cm, "_raw", None) or {})
    raw["apiVersion"] = "v1"
    raw["kind"] = "ConfigMap"
    raw["metadata"] = encode_meta(cm.metadata, raw.get("metadata"))
    raw["data"] = dict(cm.data)
    return raw


def decode_event(raw: dict) -> k8s.Event:
    inv = raw.get("involvedObject") or {}
    ev = k8s.Event(
        metadata=decode_meta(raw),
        involved_kind=inv.get("kind", ""),
        involved_name=inv.get("name", ""),
        reason=raw.get("reason", ""),
        message=raw.get("message", ""),
        type=raw.get("type", "Normal"),
    )
    ev._raw = raw  # type: ignore[attr-defined]
    return ev


def encode_event(ev: k8s.Event) -> dict:
    raw = copy.deepcopy(getattr(ev, "_raw", None) or {})
    raw["apiVersion"] = "v1"
    raw["kind"] = "Event"
    raw["metadata"] = encode_meta(ev.metadata, raw.get("metadata"))
    raw["involvedObject"] = {"kind": ev.involved_kind, "name": ev.involved_name}
    raw["reason"] = ev.reason
    raw["message"] = ev.message
    raw["type"] = ev.type
    return raw


# -- webhook configurations ---------------------------------------------------


def decode_webhook_config(raw: dict) -> k8s.WebhookConfiguration:
    whs = raw.get("webhooks") or []
    ca = b""
    if whs:
        ca = base64.b64decode(
            (whs[0].get("clientConfig") or {}).get("caBundle", "") or ""
        )
    cfg = k8s.WebhookConfiguration(
        metadata=decode_meta(raw),
        webhook_type=(
            "Mutating"
            if raw.get("kind", "").startswith("Mutating")
            else "Validating"
        ),
        ca_bundle=ca,
    )
    cfg.metadata.namespace = ""  # cluster-scoped
    cfg._raw = raw  # type: ignore[attr-defined]
    return cfg


def encode_webhook_config(cfg: k8s.WebhookConfiguration) -> dict:
    raw = copy.deepcopy(getattr(cfg, "_raw", None) or {})
    raw["apiVersion"] = "admissionregistration.k8s.io/v1"
    raw["kind"] = f"{cfg.webhook_type}WebhookConfiguration"
    raw["metadata"] = encode_meta(cfg.metadata, raw.get("metadata"))
    raw["metadata"].pop("namespace", None)
    ca64 = base64.b64encode(cfg.ca_bundle).decode()
    whs = raw.get("webhooks") or []
    for wh in whs:
        wh.setdefault("clientConfig", {})["caBundle"] = ca64
    raw["webhooks"] = whs
    return raw


# -- custom resources ---------------------------------------------------------


def _decode_claim(raw: dict | None) -> VolumeClaimSource | None:
    if not raw:
        return None
    return VolumeClaimSource(claim_name=raw.get("claimName", ""),
                             read_only=bool(raw.get("readOnly")))


def _encode_claim(vc: VolumeClaimSource) -> dict:
    return {"claimName": vc.claim_name, "readOnly": vc.read_only}


def decode_checkpoint(raw: dict) -> Checkpoint:
    spec = raw.get("spec") or {}
    st = raw.get("status") or {}
    ck = Checkpoint(
        metadata=decode_meta(raw),
        spec=CheckpointSpec(
            pod_name=spec.get("podName", ""),
            volume_claim=_decode_claim(spec.get("volumeClaim")),
            auto_migration=bool(spec.get("autoMigration")),
            pre_copy=bool(spec.get("preCopy")),
            consistent_cut=bool(spec.get("consistentCut", True)),
            ttl_seconds_after_finished=spec.get("ttlSecondsAfterFinished"),
        ),
        status=CheckpointStatus(
            node_name=st.get("nodeName", ""),
            pod_spec_hash=st.get("podSpecHash", ""),
            pod_uid=st.get("podUID", ""),
            phase=CheckpointPhase(st["phase"]) if st.get("phase") else None,
            conditions=_decode_conditions(st.get("conditions")),
            data_path=st.get("dataPath", ""),
        ),
    )
    ck._raw = raw  # type: ignore[attr-defined]
    return ck


def encode_checkpoint(ck: Checkpoint) -> dict:
    raw = copy.deepcopy(getattr(ck, "_raw", None) or {})
    raw["apiVersion"] = f"{GROUP}/{VERSION}"
    raw["kind"] = "Checkpoint"
    raw["metadata"] = encode_meta(ck.metadata, raw.get("metadata"))
    spec: dict = {"podName": ck.spec.pod_name}
    if ck.spec.volume_claim is not None:
        spec["volumeClaim"] = _encode_claim(ck.spec.volume_claim)
    if ck.spec.auto_migration:
        spec["autoMigration"] = True
    if ck.spec.pre_copy:
        spec["preCopy"] = True
    if not ck.spec.consistent_cut:
        spec["consistentCut"] = False  # default-true: only record opt-out
    if ck.spec.ttl_seconds_after_finished is not None:
        spec["ttlSecondsAfterFinished"] = int(ck.spec.ttl_seconds_after_finished)
    raw["spec"] = spec
    status: dict = {}
    if ck.status.node_name:
        status["nodeName"] = ck.status.node_name
    if ck.status.pod_spec_hash:
        status["podSpecHash"] = ck.status.pod_spec_hash
    if ck.status.pod_uid:
        status["podUID"] = ck.status.pod_uid
    if ck.status.phase is not None:
        status["phase"] = ck.status.phase.value
    if ck.status.conditions:
        status["conditions"] = _encode_conditions(ck.status.conditions)
    if ck.status.data_path:
        status["dataPath"] = ck.status.data_path
    raw["status"] = status
    return raw


def _decode_owner_ref(raw: dict | None) -> k8s.OwnerReference | None:
    if not raw:
        return None
    return k8s.OwnerReference(
        api_version=raw.get("apiVersion", ""),
        kind=raw.get("kind", ""),
        name=raw.get("name", ""),
        uid=raw.get("uid", ""),
        controller=bool(raw.get("controller")),
    )


def _encode_owner_ref(r: k8s.OwnerReference) -> dict:
    return {
        "apiVersion": r.api_version,
        "kind": r.kind,
        "name": r.name,
        "uid": r.uid,
        "controller": r.controller,
    }


def decode_restore(raw: dict) -> Restore:
    spec = raw.get("spec") or {}
    st = raw.get("status") or {}
    sel = spec.get("selector")
    rst = Restore(
        metadata=decode_meta(raw),
        spec=RestoreSpec(
            checkpoint_name=spec.get("checkpointName", ""),
            owner_ref=_decode_owner_ref(spec.get("ownerRef")),
            selector=(
                k8s.LabelSelector(match_labels=dict(sel.get("matchLabels") or {}))
                if sel
                else None
            ),
        ),
        status=RestoreStatus(
            node_name=st.get("nodeName", ""),
            target_pod=st.get("targetPod", ""),
            phase=RestorePhase(st["phase"]) if st.get("phase") else None,
            conditions=_decode_conditions(st.get("conditions")),
        ),
    )
    rst._raw = raw  # type: ignore[attr-defined]
    return rst


def encode_restore(rst: Restore) -> dict:
    raw = copy.deepcopy(getattr(rst, "_raw", None) or {})
    raw["apiVersion"] = f"{GROUP}/{VERSION}"
    raw["kind"] = "Restore"
    raw["metadata"] = encode_meta(rst.metadata, raw.get("metadata"))
    spec: dict = {"checkpointName": rst.spec.checkpoint_name}
    if rst.spec.owner_ref is not None:
        spec["ownerRef"] = _encode_owner_ref(rst.spec.owner_ref)
    if rst.spec.selector is not None:
        spec["selector"] = {"matchLabels": dict(rst.spec.selector.match_labels)}
    raw["spec"] = spec
    status: dict = {}
    if rst.status.node_name:
        status["nodeName"] = rst.status.node_name
    if rst.status.target_pod:
        status["targetPod"] = rst.status.target_pod
    if rst.status.phase is not None:
        status["phase"] = rst.status.phase.value
    if rst.status.conditions:
        status["conditions"] = _encode_conditions(rst.status.conditions)
    raw["status"] = status
    return raw


def decode_migrationplan(raw: dict) -> MigrationPlan:
    spec = raw.get("spec") or {}
    st = raw.get("status") or {}
    budget = spec.get("budget") or {}
    plan = MigrationPlan(
        metadata=decode_meta(raw),
        spec=MigrationPlanSpec(
            members=[
                MigrationPlanMember(
                    pod_name=m.get("podName", ""),
                    volume_claim=_decode_claim(m.get("volumeClaim")),
                )
                for m in (spec.get("members") or [])
            ],
            volume_claim=_decode_claim(spec.get("volumeClaim")),
            destinations=[
                MigrationPlanDestination(
                    node_name=d.get("nodeName", ""),
                    capacity_gb=float(d.get("capacityGb", 0.0) or 0.0),
                    topology=d.get("topology", ""),
                )
                for d in (spec.get("destinations") or [])
            ],
            budget=MigrationPlanBudget(
                max_concurrent=int(budget.get("maxConcurrent", 0) or 0),
                link_bandwidth_bps=float(
                    budget.get("linkBandwidthBps", 0.0) or 0.0),
                fleet_bandwidth_bps=float(
                    budget.get("fleetBandwidthBps", 0.0) or 0.0),
            ),
            pre_copy=bool(spec.get("preCopy", True)),
            max_retries_per_pod=int(spec.get("maxRetriesPerPod", -1)),
            ttl_seconds_after_finished=spec.get("ttlSecondsAfterFinished"),
        ),
        status=MigrationPlanStatus(
            phase=(MigrationPlanPhase(st["phase"])
                   if st.get("phase") else None),
            conditions=_decode_conditions(st.get("conditions")),
            pods=list(st.get("pods") or []),
            budget=dict(st.get("budget") or {}),
            started_at=_from_rfc3339(st.get("startedAt")),
            finished_at=_from_rfc3339(st.get("finishedAt")),
            makespan_seconds=float(st.get("makespanSeconds", 0.0) or 0.0),
        ),
    )
    plan._raw = raw  # type: ignore[attr-defined]
    return plan


def encode_migrationplan(plan: MigrationPlan) -> dict:
    raw = copy.deepcopy(getattr(plan, "_raw", None) or {})
    raw["apiVersion"] = f"{GROUP}/{VERSION}"
    raw["kind"] = "MigrationPlan"
    raw["metadata"] = encode_meta(plan.metadata, raw.get("metadata"))
    spec: dict = {
        "members": [
            {
                "podName": m.pod_name,
                **(
                    {"volumeClaim": _encode_claim(m.volume_claim)}
                    if m.volume_claim is not None
                    else {}
                ),
            }
            for m in plan.spec.members
        ],
        "destinations": [
            {
                "nodeName": d.node_name,
                **({"capacityGb": d.capacity_gb} if d.capacity_gb else {}),
                **({"topology": d.topology} if d.topology else {}),
            }
            for d in plan.spec.destinations
        ],
    }
    if plan.spec.volume_claim is not None:
        spec["volumeClaim"] = _encode_claim(plan.spec.volume_claim)
    b = plan.spec.budget
    budget: dict = {}
    if b.max_concurrent:
        budget["maxConcurrent"] = b.max_concurrent
    if b.link_bandwidth_bps:
        budget["linkBandwidthBps"] = b.link_bandwidth_bps
    if b.fleet_bandwidth_bps:
        budget["fleetBandwidthBps"] = b.fleet_bandwidth_bps
    if budget:
        spec["budget"] = budget
    if not plan.spec.pre_copy:
        spec["preCopy"] = False  # default-true: only record opt-out
    if plan.spec.max_retries_per_pod >= 0:
        spec["maxRetriesPerPod"] = plan.spec.max_retries_per_pod
    if plan.spec.ttl_seconds_after_finished is not None:
        spec["ttlSecondsAfterFinished"] = int(
            plan.spec.ttl_seconds_after_finished)
    raw["spec"] = spec
    status: dict = {}
    if plan.status.phase is not None:
        status["phase"] = plan.status.phase.value
    if plan.status.conditions:
        status["conditions"] = _encode_conditions(plan.status.conditions)
    if plan.status.pods:
        status["pods"] = list(plan.status.pods)
    if plan.status.budget:
        status["budget"] = dict(plan.status.budget)
    if plan.status.started_at:
        status["startedAt"] = _to_rfc3339(plan.status.started_at)
    if plan.status.finished_at:
        status["finishedAt"] = _to_rfc3339(plan.status.finished_at)
    if plan.status.makespan_seconds:
        status["makespanSeconds"] = plan.status.makespan_seconds
    raw["status"] = status
    return raw


def decode_restoreset(raw: dict) -> RestoreSet:
    spec = raw.get("spec") or {}
    st = raw.get("status") or {}
    tmpl = spec.get("template") or {}
    sel = tmpl.get("selector")
    rs = RestoreSet(
        metadata=decode_meta(raw),
        spec=RestoreSetSpec(
            snapshot_ref=spec.get("snapshotRef", ""),
            # 0 must survive decoding: the validating webhook's
            # "replicas >= 1" gate is what refuses it (an `or 1`
            # coercion here would silently fan out a clone the
            # operator asked NOT to have).
            replicas=(1 if spec.get("replicas") is None
                      else int(spec["replicas"])),
            template=RestoreSetTemplate(
                owner_ref=_decode_owner_ref(tmpl.get("ownerRef")),
                selector=(
                    k8s.LabelSelector(
                        match_labels=dict(sel.get("matchLabels") or {}))
                    if sel else None
                ),
            ),
        ),
        status=RestoreSetStatus(
            phase=(RestoreSetPhase(st["phase"])
                   if st.get("phase") else None),
            conditions=_decode_conditions(st.get("conditions")),
            replicas=list(st.get("replicas") or []),
            ready_replicas=int(st.get("readyReplicas", 0) or 0),
            progress=dict(st.get("progress") or {}),
            started_at=_from_rfc3339(st.get("startedAt")),
            finished_at=_from_rfc3339(st.get("finishedAt")),
        ),
    )
    rs._raw = raw  # type: ignore[attr-defined]
    return rs


def encode_restoreset(rs: RestoreSet) -> dict:
    raw = copy.deepcopy(getattr(rs, "_raw", None) or {})
    raw["apiVersion"] = f"{GROUP}/{VERSION}"
    raw["kind"] = "RestoreSet"
    raw["metadata"] = encode_meta(rs.metadata, raw.get("metadata"))
    spec: dict = {
        "snapshotRef": rs.spec.snapshot_ref,
        "replicas": int(rs.spec.replicas),
    }
    tmpl: dict = {}
    if rs.spec.template.owner_ref is not None:
        tmpl["ownerRef"] = _encode_owner_ref(rs.spec.template.owner_ref)
    if rs.spec.template.selector is not None:
        tmpl["selector"] = {
            "matchLabels": dict(rs.spec.template.selector.match_labels)}
    if tmpl:
        spec["template"] = tmpl
    raw["spec"] = spec
    status: dict = {}
    if rs.status.phase is not None:
        status["phase"] = rs.status.phase.value
    if rs.status.conditions:
        status["conditions"] = _encode_conditions(rs.status.conditions)
    if rs.status.replicas:
        status["replicas"] = list(rs.status.replicas)
    if rs.status.ready_replicas:
        status["readyReplicas"] = int(rs.status.ready_replicas)
    if rs.status.progress:
        status["progress"] = dict(rs.status.progress)
    if rs.status.started_at:
        status["startedAt"] = _to_rfc3339(rs.status.started_at)
    if rs.status.finished_at:
        status["finishedAt"] = _to_rfc3339(rs.status.finished_at)
    raw["status"] = status
    return raw


# -- kind registry ------------------------------------------------------------


@dataclass(frozen=True)
class KindInfo:
    kind: str
    api_prefix: str  # "/api/v1" | "/apis/batch/v1" | ...
    plural: str
    namespaced: bool
    decode: Callable[[dict], Any]
    encode: Callable[[Any], dict]
    has_status_subresource: bool = False


KINDS: dict[str, KindInfo] = {
    "Pod": KindInfo("Pod", "/api/v1", "pods", True, decode_pod, encode_pod),
    "Job": KindInfo("Job", "/apis/batch/v1", "jobs", True, decode_job, encode_job),
    "Node": KindInfo("Node", "/api/v1", "nodes", False, decode_node, encode_node),
    "PersistentVolumeClaim": KindInfo(
        "PersistentVolumeClaim", "/api/v1", "persistentvolumeclaims", True,
        decode_pvc, encode_pvc,
    ),
    "Secret": KindInfo(
        "Secret", "/api/v1", "secrets", True, decode_secret, encode_secret
    ),
    "ConfigMap": KindInfo(
        "ConfigMap", "/api/v1", "configmaps", True, decode_configmap,
        encode_configmap,
    ),
    "Event": KindInfo(
        "Event", "/api/v1", "events", True, decode_event, encode_event
    ),
    "Checkpoint": KindInfo(
        "Checkpoint", f"/apis/{GROUP}/{VERSION}", "checkpoints", True,
        decode_checkpoint, encode_checkpoint, has_status_subresource=True,
    ),
    "Restore": KindInfo(
        "Restore", f"/apis/{GROUP}/{VERSION}", "restores", True,
        decode_restore, encode_restore, has_status_subresource=True,
    ),
    "MigrationPlan": KindInfo(
        "MigrationPlan", f"/apis/{GROUP}/{VERSION}", "migrationplans",
        True, decode_migrationplan, encode_migrationplan,
        has_status_subresource=True,
    ),
    "RestoreSet": KindInfo(
        "RestoreSet", f"/apis/{GROUP}/{VERSION}", "restoresets", True,
        decode_restoreset, encode_restoreset,
        has_status_subresource=True,
    ),
    "ValidatingWebhookConfiguration": KindInfo(
        "ValidatingWebhookConfiguration",
        "/apis/admissionregistration.k8s.io/v1",
        "validatingwebhookconfigurations", False,
        decode_webhook_config, encode_webhook_config,
    ),
    "MutatingWebhookConfiguration": KindInfo(
        "MutatingWebhookConfiguration",
        "/apis/admissionregistration.k8s.io/v1",
        "mutatingwebhookconfigurations", False,
        decode_webhook_config, encode_webhook_config,
    ),
}


def kind_info(kind: str, obj: Any = None) -> KindInfo:
    """Resolve kind → KindInfo. The typed ``WebhookConfiguration`` maps onto
    two REST kinds; ``obj.webhook_type`` disambiguates."""
    if kind == "WebhookConfiguration":
        wt = getattr(obj, "webhook_type", "Validating")
        kind = f"{wt}WebhookConfiguration"
    info = KINDS.get(kind)
    if info is None:
        raise KeyError(f"no codec for kind {kind!r}")
    return info


def resource_path(
    info: KindInfo, namespace: str | None = None, name: str | None = None,
    subresource: str | None = None,
) -> str:
    parts = [info.api_prefix]
    if info.namespaced and namespace:
        parts.append(f"namespaces/{namespace}")
    parts.append(info.plural)
    if name:
        parts.append(name)
    if subresource:
        parts.append(subresource)
    return "/".join(p.strip("/") for p in parts if p).join(["/", ""])
