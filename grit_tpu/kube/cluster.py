"""In-process Kubernetes API server: typed store + admission + watch.

Plays the role the kube-apiserver plays between the reference's components
(SURVEY §1: "control flow between layers is decoupled through the Kubernetes
API"). Semantics implemented: namespaced CRUD with UID/resourceVersion,
optimistic-concurrency conflicts, mutating→validating admission on CREATE,
read-modify-write ``patch`` helper with retry, and watch events feeding
controller workqueues (:mod:`grit_tpu.kube.controller`).
"""

from __future__ import annotations

import itertools
import threading
from collections.abc import Callable, Iterable
from dataclasses import dataclass
from typing import Any

from grit_tpu.kube.objects import ObjectMeta, deep_copy, now


class NotFound(Exception):
    pass


class Conflict(Exception):
    """resourceVersion mismatch on update (optimistic concurrency)."""


class AlreadyExists(Exception):
    pass


class AdmissionDenied(Exception):
    """A validating webhook rejected the object (fail-closed webhooks on our
    own CRs; pod webhook is fail-open — reference pod_restore_default.go:119)."""


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    kind: str
    namespace: str
    name: str
    obj: Any


# Admission webhook signature: fn(cluster, obj) -> None. Mutating webhooks
# mutate obj in place; validating webhooks raise AdmissionDenied.
AdmissionHook = Callable[["Cluster", Any], None]
WatchHandler = Callable[[WatchEvent], None]


class Cluster:
    """Thread-safe in-process API server."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._store: dict[tuple[str, str, str], Any] = {}
        self._creating: set[tuple[str, str, str]] = set()
        self._uid_counter = itertools.count(1)
        self._rv_counter = itertools.count(1)
        self._current_rv = 0
        self._mutating: dict[str, list[tuple[AdmissionHook, bool]]] = {}
        self._validating: dict[str, list[tuple[AdmissionHook, bool]]] = {}
        self._watchers: list[tuple[str | None, WatchHandler]] = []

    # -- admission registration -------------------------------------------------

    def register_mutating_webhook(
        self, kind: str, hook: AdmissionHook, *, fail_open: bool = False
    ) -> None:
        self._mutating.setdefault(kind, []).append((hook, fail_open))

    def register_validating_webhook(
        self, kind: str, hook: AdmissionHook, *, fail_open: bool = False
    ) -> None:
        self._validating.setdefault(kind, []).append((hook, fail_open))

    # -- watch ------------------------------------------------------------------

    def watch(self, kind: str | None, handler: WatchHandler) -> None:
        """Register a watch handler; kind=None watches everything."""

        with self._lock:
            self._watchers.append((kind, handler))

    def _emit(self, event_type: str, obj: Any) -> None:
        meta: ObjectMeta = obj.metadata
        ev = WatchEvent(event_type, obj.kind, meta.namespace, meta.name, deep_copy(obj))
        for kind, handler in list(self._watchers):
            if kind is None or kind == obj.kind:
                handler(ev)

    # -- CRUD -------------------------------------------------------------------

    def _key(self, kind: str, namespace: str, name: str) -> tuple[str, str, str]:
        return (kind, namespace, name)

    def create(self, obj: Any) -> Any:
        """CREATE with admission. Mutating hooks run first (and may annotate
        the object and/or patch *other* objects through the cluster handle,
        like the pod webhook claiming a Restore), then validating hooks."""

        kind = obj.kind
        obj = deep_copy(obj)
        # Uniqueness reservation before admission: mutating webhooks may have
        # side effects on *other* objects (the pod webhook claims a Restore),
        # which must not fire for a create that is doomed to AlreadyExists.
        # The reservation also serialises concurrent same-name creates so
        # exactly one of them runs admission.
        key = self._key(kind, obj.metadata.namespace, obj.metadata.name)
        with self._lock:
            if key in self._store or key in self._creating:
                raise AlreadyExists(f"{kind} {obj.metadata.namespace}/{obj.metadata.name}")
            self._creating.add(key)
        try:
            return self._create_admitted(obj, key)
        finally:
            with self._lock:
                self._creating.discard(key)

    def _create_admitted(self, obj: Any, key: tuple[str, str, str]) -> Any:
        kind = obj.kind
        for hook, fail_open in self._mutating.get(kind, []):
            try:
                hook(self, obj)
            except AdmissionDenied:
                if not fail_open:
                    raise
            except Exception:
                if not fail_open:
                    raise
        for hook, fail_open in self._validating.get(kind, []):
            try:
                hook(self, obj)
            except AdmissionDenied:
                if not fail_open:
                    raise
            except Exception:
                if not fail_open:
                    raise

        with self._lock:
            meta: ObjectMeta = obj.metadata
            # Mutating hooks may rewrite name/namespace: store under the
            # post-admission key and re-check uniqueness for it.
            final_key = self._key(kind, meta.namespace, meta.name)
            if final_key != key and (
                final_key in self._store or final_key in self._creating
            ):
                raise AlreadyExists(f"{kind} {meta.namespace}/{meta.name}")
            if not meta.uid:
                meta.uid = f"uid-{next(self._uid_counter)}"
            meta.resource_version = self._next_rv()
            if not meta.creation_timestamp:
                meta.creation_timestamp = now()
            self._store[final_key] = deep_copy(obj)
        self._emit("ADDED", obj)
        return deep_copy(obj)

    def _next_rv(self) -> int:
        self._current_rv = next(self._rv_counter)
        return self._current_rv

    def current_resource_version(self) -> int:
        """Monotonic store version — advances on every successful write."""

        with self._lock:
            return self._current_rv

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        with self._lock:
            obj = self._store.get(self._key(kind, namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return deep_copy(obj)

    def try_get(self, kind: str, name: str, namespace: str = "default") -> Any | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[Any]:
        with self._lock:
            out = []
            for (k, ns, _), obj in self._store.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if label_selector and not all(
                    obj.metadata.labels.get(lk) == lv for lk, lv in label_selector.items()
                ):
                    continue
                out.append(deep_copy(obj))
            return out

    def update(self, obj: Any) -> Any:
        """UPDATE with optimistic concurrency on resourceVersion."""

        with self._lock:
            meta: ObjectMeta = obj.metadata
            key = self._key(obj.kind, meta.namespace, meta.name)
            current = self._store.get(key)
            if current is None:
                raise NotFound(f"{obj.kind} {meta.namespace}/{meta.name}")
            if meta.resource_version != current.metadata.resource_version:
                raise Conflict(
                    f"{obj.kind} {meta.namespace}/{meta.name}: "
                    f"rv {meta.resource_version} != {current.metadata.resource_version}"
                )
            obj = deep_copy(obj)
            obj.metadata.resource_version = self._next_rv()
            self._store[key] = deep_copy(obj)
        self._emit("MODIFIED", obj)
        return deep_copy(obj)

    def patch(
        self,
        kind: str,
        name: str,
        mutate: Callable[[Any], None],
        namespace: str = "default",
        retries: int = 5,
    ) -> Any:
        """Read-modify-write with conflict retry (client-go RetryOnConflict
        analogue)."""

        for _ in range(retries):
            obj = self.get(kind, name, namespace)
            before = deep_copy(obj)
            mutate(obj)
            if obj == before:
                return obj  # no-op patch: don't bump rv / emit events
            try:
                return self.update(obj)
            except Conflict:
                continue
        raise Conflict(f"{kind} {namespace}/{name}: retries exhausted")

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            key = self._key(kind, namespace, name)
            obj = self._store.pop(key, None)
            if obj is not None:
                self._next_rv()  # deletes advance store state too
        if obj is None:
            raise NotFound(f"{kind} {namespace}/{name}")
        obj.metadata.deletion_timestamp = now()
        self._emit("DELETED", obj)

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    # -- helpers ----------------------------------------------------------------

    def all_objects(self) -> Iterable[Any]:
        with self._lock:
            return [deep_copy(o) for o in self._store.values()]
