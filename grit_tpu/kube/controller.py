"""Controller runtime: watch → workqueue → reconcile.

Mirrors the controller-runtime shape the reference uses (rate-limited
workqueue, N workers, requeue-on-error — reference checkpoint_controller.go
Register :290-303) in a deliberately simple, deterministic form: a
deduplicating FIFO queue per controller, drained either by worker threads
(production) or synchronously (:func:`run_until_quiescent`, tests).
"""

from __future__ import annotations

import threading
import time
from collections.abc import Callable
from dataclasses import dataclass, field
from typing import Protocol

from grit_tpu.obs.metrics import RECONCILE_ERRORS
from grit_tpu.kube.cluster import Cluster, WatchEvent


@dataclass(frozen=True)
class Request:
    namespace: str
    name: str


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0


class Reconciler(Protocol):
    #: resource kind this controller owns (its workqueue key space).
    #: May be SYNTHETIC (not a real API kind) when another controller
    #: already owns the real kind's queue — the preemption watcher keys
    #: its queue "NodePreemption" while watching Nodes; such controllers
    #: set ``watch_own_kind = False`` so the manager never asks the
    #: cluster to watch a kind the apiserver has no resource for (the
    #: REST client's watch loop would die on the unknown path).
    kind: str

    def reconcile(self, cluster: Cluster, req: Request) -> Result: ...

    def register(self, cluster: Cluster, enqueue: Callable[[Request], None]) -> None:
        """Set up watches. Default wiring (watch own kind) is done by the
        manager; controllers override to add secondary watches (e.g. the
        checkpoint controller watches agent Jobs)."""


class WorkQueue:
    """Deduplicating FIFO with optional delayed re-adds."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._items: list[Request] = []
        self._pending: set[Request] = set()
        self._delayed: list[tuple[float, Request]] = []
        self._cv = threading.Condition(self._lock)

    def add(self, req: Request) -> None:
        with self._cv:
            if req not in self._pending:
                self._pending.add(req)
                self._items.append(req)
                self._cv.notify()

    def add_after(self, req: Request, delay: float) -> None:
        with self._cv:
            self._delayed.append((time.monotonic() + delay, req))
            self._cv.notify()

    def _promote_due(self) -> None:
        t = time.monotonic()
        due = [r for when, r in self._delayed if when <= t]
        self._delayed = [(when, r) for when, r in self._delayed if when > t]
        for r in due:
            if r not in self._pending:
                self._pending.add(r)
                self._items.append(r)

    def pop(self, block: bool = False, timeout: float = 0.1) -> Request | None:
        with self._cv:
            self._promote_due()
            if not self._items and block:
                self._cv.wait(timeout)
                self._promote_due()
            if not self._items:
                return None
            req = self._items.pop(0)
            self._pending.discard(req)
            return req

    def empty(self) -> bool:
        with self._lock:
            return not self._items

    def has_delayed(self) -> bool:
        with self._lock:
            return bool(self._delayed)


class ControllerManager:
    """Assembles controllers + webhooks over one cluster handle — the analogue
    of the reference's manager Run() (cmd/grit-manager/app/manager.go:75-189),
    minus TLS/leader-election which have no meaning in-process (a real-cluster
    deployment handles those in the adapter layer; see deploy/)."""

    def __init__(self, cluster: Cluster) -> None:
        self.cluster = cluster
        self._queues: dict[str, WorkQueue] = {}
        self._reconcilers: list[Reconciler] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def add_controller(self, rec: Reconciler) -> None:
        queue = WorkQueue()
        self._queues[rec.kind] = queue

        def enqueue(req: Request) -> None:
            queue.add(req)

        # Default watch: the controller's own kind — unless the kind is
        # synthetic (a queue-keyspace alias for a kind another controller
        # owns; see Reconciler.kind) and register() wires the real watch.
        if getattr(rec, "watch_own_kind", True):
            def on_event(ev: WatchEvent) -> None:
                enqueue(Request(ev.namespace, ev.name))

            self.cluster.watch(rec.kind, on_event)
        rec.register(self.cluster, enqueue)
        self._reconcilers.append(rec)

    # -- synchronous drain (tests & single-shot convergence) --------------------

    def run_until_quiescent(self, max_rounds: int = 500) -> None:
        """Drain every queue until all are empty and a full pass produces no
        new events.

        ``requeue_after`` results are *parked* rather than re-added hot: a
        reconciler asking to poll later (e.g. waiting for a pod to start) is
        a legitimate steady state, not a livelock. A parked request is
        re-admitted only after the cluster's resource version advances —
        reconcilers are functions of cluster state, so re-running one on
        unchanged state cannot make progress.
        """

        # (reconciler, request) → cluster rv when parked.
        parked: dict[tuple[int, Request], int] = {}
        for _ in range(max_rounds):
            progressed = False
            for idx, rec in enumerate(self._reconcilers):
                queue = self._queues[rec.kind]
                # Re-admit parked requests if state moved since parking.
                rv = self.cluster.current_resource_version()
                for (pidx, preq), prv in list(parked.items()):
                    if pidx == idx and rv > prv:
                        del parked[(pidx, preq)]
                        queue.add(preq)
                readds: list[Request] = []
                while (req := queue.pop()) is not None:
                    progressed = True
                    try:
                        res = rec.reconcile(self.cluster, req)
                    except Exception:
                        RECONCILE_ERRORS.inc(controller=rec.kind)
                        queue.add(req)
                        raise
                    if res and res.requeue:
                        readds.append(req)  # next round, not the hot loop
                    elif res and res.requeue_after:
                        parked[(idx, req)] = self.cluster.current_resource_version()
                for r in readds:
                    queue.add(r)
            if not progressed:
                return
        raise RuntimeError("controllers did not converge (livelock?)")

    # -- threaded mode (production) ---------------------------------------------

    def start(self, workers_per_controller: int = 2) -> None:
        for rec in self._reconcilers:
            queue = self._queues[rec.kind]
            for i in range(workers_per_controller):
                t = threading.Thread(
                    target=self._worker, args=(rec, queue), daemon=True,
                    name=f"{rec.kind.lower()}-worker-{i}",
                )
                t.start()
                self._threads.append(t)

    def _worker(self, rec: Reconciler, queue: WorkQueue) -> None:
        while not self._stop.is_set():
            req = queue.pop(block=True)
            if req is None:
                continue
            try:
                res = rec.reconcile(self.cluster, req)
            except Exception:  # noqa: BLE001 - requeue with backoff
                RECONCILE_ERRORS.inc(controller=rec.kind)
                queue.add_after(req, 0.5)
                continue
            if res and res.requeue_after:
                queue.add_after(req, res.requeue_after)
            elif res and res.requeue:
                queue.add(req)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
