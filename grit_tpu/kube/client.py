"""Real kube-apiserver adapter implementing the :class:`Cluster` protocol.

Parity: reference ``cmd/grit-manager/app/manager.go:75-189`` builds a
controller-runtime manager over client-go; here the same role is a single
adapter class — the controllers/webhooks are transport-agnostic against the
``Cluster`` surface, so :class:`KubeCluster` makes the whole control plane
run against a live apiserver (or any server speaking the same REST subset;
the test suite runs it against an in-process fake).

Transport is stdlib-only (http.client + ssl): TLS with CA verification,
bearer-token or client-cert auth, kubeconfig and in-cluster discovery.
Watches are one background thread per kind running list+watch with
re-list on 410 Gone, feeding the same handler signature the in-memory
cluster uses.

Admission differs from the in-memory cluster by design: a real apiserver
calls back into our webhook HTTPS server (:mod:`grit_tpu.manager.
webhook_server`) during CREATE, so ``create`` here does NOT run admission
hooks locally; ``register_*_webhook`` records them for the webhook server.
"""

from __future__ import annotations

import http.client
import json
import os
import ssl
import tempfile
import threading
from typing import Any, Callable

from grit_tpu.kube.cluster import (
    AdmissionHook,
    AlreadyExists,
    Conflict,
    NotFound,
    WatchEvent,
    WatchHandler,
)
from grit_tpu.kube.codec import KINDS, KindInfo, kind_info, resource_path

IN_CLUSTER_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"
IN_CLUSTER_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"apiserver {status}: {message}")
        self.status = status


class KubeConfig:
    """Connection parameters for one apiserver."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        scheme: str = "https",
        token: str | None = None,
        ssl_context: ssl.SSLContext | None = None,
    ) -> None:
        self.host = host
        self.port = port
        self.scheme = scheme
        self.token = token
        self.ssl_context = ssl_context

    @classmethod
    def from_url(cls, url: str, **kw) -> "KubeConfig":
        scheme, rest = url.split("://", 1)
        hostport = rest.split("/", 1)[0]
        host, _, port = hostport.partition(":")
        return cls(
            host, int(port or (443 if scheme == "https" else 80)),
            scheme=scheme, **kw,
        )

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        """Pod-mounted serviceaccount config (client-go rest.InClusterConfig
        analogue)."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError("not running in a cluster (no KUBERNETES_SERVICE_HOST)")
        ctx = ssl.create_default_context(cafile=IN_CLUSTER_CA)
        with open(IN_CLUSTER_TOKEN) as f:
            token = f.read().strip()
        return cls(host, int(port), token=token, ssl_context=ctx)

    @classmethod
    def from_kubeconfig(cls, path: str | None = None, context: str | None = None) -> "KubeConfig":
        """Parse a kubeconfig file (the subset kubectl itself needs:
        clusters/users/contexts with inline or file CA/client credentials)."""
        import base64

        import yaml

        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config")
        )
        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = context or cfg.get("current-context")
        ctx = next(
            c["context"] for c in cfg["contexts"] if c["name"] == ctx_name
        )
        cluster = next(
            c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"]
        )
        user = next(
            u["user"] for u in cfg["users"] if u["name"] == ctx["user"]
        )

        server = cluster["server"]
        sslctx: ssl.SSLContext | None = None
        if server.startswith("https"):
            if cluster.get("insecure-skip-tls-verify"):
                sslctx = ssl._create_unverified_context()  # noqa: S323 - explicit opt-in
            elif "certificate-authority-data" in cluster:
                sslctx = ssl.create_default_context(
                    cadata=base64.b64decode(
                        cluster["certificate-authority-data"]
                    ).decode()
                )
            elif "certificate-authority" in cluster:
                sslctx = ssl.create_default_context(
                    cafile=cluster["certificate-authority"]
                )
            else:
                sslctx = ssl.create_default_context()
            cert = user.get("client-certificate") or user.get(
                "client-certificate-data"
            )
            key = user.get("client-key") or user.get("client-key-data")
            if cert and key:
                if "client-certificate-data" in user:
                    # ssl wants files; materialize inline creds.
                    cf = tempfile.NamedTemporaryFile("w", delete=False, suffix=".crt")
                    cf.write(base64.b64decode(user["client-certificate-data"]).decode())
                    cf.close()
                    kf = tempfile.NamedTemporaryFile("w", delete=False, suffix=".key")
                    kf.write(base64.b64decode(user["client-key-data"]).decode())
                    kf.close()
                    cert, key = cf.name, kf.name
                sslctx.load_cert_chain(cert, key)
        return cls.from_url(
            server, token=user.get("token"), ssl_context=sslctx
        )


class KubeApi:
    """Minimal REST transport: JSON request/response + streaming watch."""

    def __init__(self, config: KubeConfig, timeout: float = 30.0) -> None:
        self.config = config
        self.timeout = timeout

    def _conn(self, timeout: float | None = None) -> http.client.HTTPConnection:
        t = timeout if timeout is not None else self.timeout
        if self.config.scheme == "https":
            return http.client.HTTPSConnection(
                self.config.host, self.config.port,
                context=self.config.ssl_context, timeout=t,
            )
        return http.client.HTTPConnection(
            self.config.host, self.config.port, timeout=t
        )

    def _headers(self) -> dict:
        h = {"Accept": "application/json", "Content-Type": "application/json"}
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        return h

    def request(
        self, method: str, path: str, body: dict | None = None,
        query: str = "",
    ) -> dict:
        conn = self._conn()
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path + query, body=payload, headers=self._headers())
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 404:
                raise NotFound(f"{method} {path}: not found")
            if resp.status == 409:
                msg = data.decode(errors="replace")
                if "AlreadyExists" in msg or method == "POST":
                    raise AlreadyExists(f"{method} {path}: {msg[:200]}")
                raise Conflict(f"{method} {path}: {msg[:200]}")
            if resp.status >= 400:
                raise ApiError(resp.status, f"{method} {path}: {data[:300]!r}")
            return json.loads(data) if data else {}
        finally:
            conn.close()

    def stream_watch(
        self, path: str, query: str, on_event: Callable[[dict], None],
        stopped: threading.Event,
    ) -> None:
        """One watch connection: newline-delimited JSON events until EOF."""
        conn = self._conn(timeout=330.0)  # server timeoutSeconds + slack
        try:
            conn.request("GET", path + query, headers=self._headers())
            resp = conn.getresponse()
            if resp.status == 410:
                raise ApiError(410, "watch expired")
            if resp.status >= 400:
                raise ApiError(resp.status, resp.read()[:200].decode(errors="replace"))
            buf = b""
            while not stopped.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        on_event(json.loads(line))
        finally:
            conn.close()


class KubeCluster:
    """Cluster-protocol adapter over a real (or fake) kube-apiserver."""

    def __init__(self, config: KubeConfig, namespace: str = "default") -> None:
        self.api = KubeApi(config)
        self.namespace = namespace
        self._lock = threading.RLock()
        self._watchers: dict[str, list[WatchHandler]] = {}
        self._watch_threads: dict[str, threading.Thread] = {}
        self._watch_stop = threading.Event()
        self._rv = 0
        # Recorded for the webhook HTTPS server; a real apiserver calls
        # admission through it, never locally.
        self.mutating_hooks: dict[str, list[tuple[AdmissionHook, bool]]] = {}
        self.validating_hooks: dict[str, list[tuple[AdmissionHook, bool]]] = {}

    # -- admission registration (consumed by the webhook server) ----------------

    def register_mutating_webhook(self, kind: str, hook: Any, *,
                                  fail_open: bool = False) -> None:
        self.mutating_hooks.setdefault(kind, []).append((hook, fail_open))

    def register_validating_webhook(self, kind: str, hook: Any, *,
                                    fail_open: bool = False) -> None:
        self.validating_hooks.setdefault(kind, []).append((hook, fail_open))

    # -- bookkeeping -------------------------------------------------------------

    def _bump(self, raw: dict | None = None) -> None:
        with self._lock:
            rv = 0
            if raw:
                try:
                    rv = int((raw.get("metadata") or {}).get("resourceVersion", 0))
                except (TypeError, ValueError):
                    rv = 0
            self._rv = max(self._rv + 1, rv)

    def current_resource_version(self) -> int:
        with self._lock:
            return self._rv

    # -- CRUD --------------------------------------------------------------------

    def _info(self, kind: str, obj: Any = None) -> KindInfo:
        return kind_info(kind, obj)

    def create(self, obj: Any) -> Any:
        info = self._info(obj.kind, obj)
        raw = info.encode(obj)
        ns = obj.metadata.namespace if info.namespaced else None
        out = self.api.request("POST", resource_path(info, ns), body=raw)
        return self._decode(info, out) if out else obj

    def _decode(self, info: KindInfo, raw: dict) -> Any:
        self._bump(raw)
        return info.decode(raw)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        if kind == "WebhookConfiguration":
            for k in ("ValidatingWebhookConfiguration", "MutatingWebhookConfiguration"):
                try:
                    info = KINDS[k]
                    raw = self.api.request("GET", resource_path(info, None, name))
                    return self._decode(info, raw)
                except NotFound:
                    continue
            raise NotFound(f"WebhookConfiguration {name}")
        info = self._info(kind)
        ns = namespace if info.namespaced else None
        raw = self.api.request("GET", resource_path(info, ns, name))
        return self._decode(info, raw)

    def try_get(self, kind: str, name: str, namespace: str = "default") -> Any | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[Any]:
        infos = (
            [KINDS["ValidatingWebhookConfiguration"], KINDS["MutatingWebhookConfiguration"]]
            if kind == "WebhookConfiguration"
            else [self._info(kind)]
        )
        query = ""
        if label_selector:
            import urllib.parse

            sel = ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))
            query = "?" + urllib.parse.urlencode({"labelSelector": sel})
        out: list[Any] = []
        for info in infos:
            ns = namespace if info.namespaced else None
            try:
                raw = self.api.request("GET", resource_path(info, ns), query=query)
            except NotFound:
                continue
            for item in raw.get("items", []):
                item.setdefault("kind", info.kind)
                out.append(info.decode(item))
        return out

    def update(self, obj: Any) -> Any:
        info = self._info(obj.kind, obj)
        raw = info.encode(obj)
        old = getattr(obj, "_raw", None) or {}
        ns = obj.metadata.namespace if info.namespaced else None
        name = obj.metadata.name
        status_changed = raw.get("status") != old.get("status")
        main_changed = {
            k: v for k, v in raw.items() if k != "status"
        } != {k: v for k, v in old.items() if k != "status"}

        current = raw
        if main_changed or not info.has_status_subresource or not old:
            current = self.api.request(
                "PUT", resource_path(info, ns, name), body=raw
            )
        if info.has_status_subresource and status_changed:
            body = dict(current)
            body["status"] = raw.get("status", {})
            current = self.api.request(
                "PUT", resource_path(info, ns, name, "status"), body=body
            )
        return self._decode(info, current)

    def patch(
        self,
        kind: str,
        name: str,
        mutate: Callable[[Any], None],
        namespace: str = "default",
        retries: int = 5,
    ) -> Any:
        for _ in range(retries):
            obj = self.get(kind, name, namespace)
            info = self._info(kind, obj)
            before = info.encode(obj)
            mutate(obj)
            after = info.encode(obj)
            if before == after:
                return obj
            try:
                return self.update(obj)
            except Conflict:
                continue
        raise Conflict(f"{kind} {namespace}/{name}: retries exhausted")

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        info = self._info(kind)
        ns = namespace if info.namespaced else None
        self.api.request("DELETE", resource_path(info, ns, name))
        self._bump()

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    # -- watch -------------------------------------------------------------------

    def watch(self, kind: str | None, handler: WatchHandler) -> None:
        if kind is None:
            raise ValueError(
                "KubeCluster.watch requires an explicit kind "
                "(wildcard watch is an in-memory-cluster convenience)"
            )
        with self._lock:
            self._watchers.setdefault(kind, []).append(handler)
            rest_kinds = (
                ["ValidatingWebhookConfiguration", "MutatingWebhookConfiguration"]
                if kind == "WebhookConfiguration"
                else [kind]
            )
            for rk in rest_kinds:
                if rk not in self._watch_threads:
                    t = threading.Thread(
                        target=self._watch_loop, args=(rk, kind),
                        name=f"kube-watch-{rk.lower()}", daemon=True,
                    )
                    self._watch_threads[rk] = t
                    t.start()

    def stop_watches(self) -> None:
        self._watch_stop.set()

    def _dispatch_event(self, typed_kind: str, ev_type: str, obj: Any) -> None:
        self._bump(getattr(obj, "_raw", None))
        ev = WatchEvent(
            ev_type, typed_kind, obj.metadata.namespace, obj.metadata.name, obj
        )
        for handler in list(self._watchers.get(typed_kind, [])):
            try:
                handler(ev)
            except Exception:  # noqa: BLE001 - a handler must not kill the watch
                pass

    def _watch_loop(self, rest_kind: str, typed_kind: str) -> None:
        import time as _time

        from grit_tpu.retry import Backoff

        info = KINDS[rest_kind]
        # Cluster-wide, matching controller-runtime's informers and this
        # class's list(namespace=None) (advisor r2: a namespace-scoped watch
        # would blind controllers to CRs created outside self.namespace).
        path = resource_path(info, None)
        rv: str | None = None
        # Reconnect schedule: capped exponential backoff + jitter instead
        # of a fixed 0.2/0.5 s — N manager replicas hammering a flapping
        # apiserver in lockstep is exactly the thundering herd that keeps
        # it down. Any successfully decoded watch event resets the streak
        # (the apiserver is serving again; the next hiccup starts cheap).
        backoff = Backoff(base=0.2, cap=30.0, jitter=0.5)
        while not self._watch_stop.is_set():
            try:
                if rv is None:
                    raw = self.api.request("GET", path)
                    rv = (raw.get("metadata") or {}).get("resourceVersion", "0")
                    for item in raw.get("items", []):
                        item.setdefault("kind", info.kind)
                        self._dispatch_event(
                            typed_kind, "ADDED", info.decode(item)
                        )

                def on_raw(ev: dict) -> None:
                    nonlocal rv
                    backoff.reset()  # live events == healthy apiserver
                    etype = ev.get("type", "")
                    if etype == "BOOKMARK":
                        rv = (ev.get("object", {}).get("metadata") or {}).get(
                            "resourceVersion", rv
                        )
                        return
                    if etype not in ("ADDED", "MODIFIED", "DELETED"):
                        return
                    item = ev["object"]
                    item.setdefault("kind", info.kind)
                    obj = info.decode(item)
                    rv = (item.get("metadata") or {}).get("resourceVersion", rv)
                    self._dispatch_event(typed_kind, etype, obj)

                self.api.stream_watch(
                    path,
                    f"?watch=true&resourceVersion={rv}&allowWatchBookmarks=true",
                    on_raw,
                    self._watch_stop,
                )
            except ApiError as exc:
                if exc.status == 410:
                    rv = None  # expired: full re-list
                self._watch_stop.wait(backoff.next())
            except (OSError, NotFound, ValueError, KeyError):
                self._watch_stop.wait(backoff.next())
            else:
                # stream_watch returned without error (server closed the
                # stream politely): reconnect promptly.
                _time.sleep(0.05)

    # -- helpers -----------------------------------------------------------------

    def all_objects(self) -> list[Any]:
        out = []
        for kind in ("Pod", "Job", "Checkpoint", "Restore", "Secret", "ConfigMap"):
            try:
                out.extend(self.list(kind))
            except (NotFound, ApiError):
                continue
        return out
