"""Node-level migration harness — shared by the e2e tests and bench.

Drives the full BASELINE config-2 shape minus real containerd: a
deterministic MNIST trainer (Trainer + Agentlet) runs as a real OS process;
the agent checkpoint driver quiesces it through the toggle path and dumps
HBM state into the container checkpoint layout; the data mover ships it to
the "PVC"; the process is killed (blackout); the restore agent stages data;
the shim turns the replacement create into a restore and injects the HBM
env; a fresh process resumes training bit-identically.

Reference shape: ``contrib/containerd/testdata/{run.sh,restore.sh}`` (the
crictl-level manual e2e) + ``docs/experiments/checkpoint-restore-tuning-job
.md:98-148`` (dump at step N, resume N+1→end).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import textwrap

from grit_tpu.agent.abort import AbortOptions, run_abort
from grit_tpu.agent.checkpoint import (
    CheckpointOptions,
    run_checkpoint,
    run_precopy_phase,
)
from grit_tpu.agent.restore import (
    RestoreOptions,
    StreamedRestore,
    WireRestore,
    run_prestage,
    run_restore,
    run_restore_streamed,
    run_restore_wire,
)
from grit_tpu.api import config
from grit_tpu.api.constants import CHECKPOINT_DATA_PATH_ANNOTATION
from grit_tpu.cri.runtime import (
    Container,
    FakeRuntime,
    OciSpec,
    Sandbox,
    SimProcess,
)
from grit_tpu.device.hook import AutoDeviceHook, RESTORE_ENV

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Deterministic trainer workload: same seed → same loss sequence in any
# process. Prints "STEP <n> <loss>" after each step; restores from the shim
# env transparently via maybe_restore_from_env(). Pinned to CPU: the harness
# measures orchestration, and the host process may own the TPU.
WORKLOAD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from functools import partial
    from grit_tpu.models import mnist
    from grit_tpu.train import Trainer
    from grit_tpu.device.agentlet import Agentlet

    cfg = mnist.MnistConfig(hidden_dim=16)
    tr = Trainer(
        loss_fn=partial(mnist.loss_fn, cfg),
        init_params=partial(mnist.init_params, cfg),
        batch_fn=lambda rng: mnist.synthetic_batch(cfg, rng, 16),
    )
    restored = tr.maybe_restore_from_env()
    if restored is not None:
        print(f"RESTORED {{restored}}", flush=True)
    agentlet = Agentlet(lambda: tr.state, step_fn=lambda: tr.step).start()
    print("READY", flush=True)
    n_steps = int(os.environ.get("N_STEPS", "10"))
    while tr.step < n_steps:
        loss = float(tr.train_step()["loss"])
        print(f"STEP {{tr.step}} {{loss!r}}", flush=True)
        agentlet.checkpoint_point()
    print("DONE", flush=True)
""").format(repo=REPO)


def read_losses(lines) -> dict[int, float]:
    out = {}
    for line in lines:
        m = re.match(r"STEP (\d+) (.+)", line)
        if m:
            out[int(m.group(1))] = float(m.group(2))
    return out


class WorkloadExited(RuntimeError):
    pass


class MigrationHarness:
    """One source→destination migration over a base directory.

    Layout: ``<base>/socks`` (agentlet sockets), ``<base>/host/...`` (source
    node work dir), ``<base>/pvc/...`` (shared store), ``<base>/dst/...``
    (destination node staging).
    """

    def __init__(self, base_dir: str, pod: str = "train", namespace: str = "ns1",
                 workload_src: str | None = None):
        self.base = str(base_dir)
        self.pod = pod
        self.namespace = namespace
        self.workload_src = workload_src or WORKLOAD
        self.sockdir = os.path.join(self.base, "socks")
        self.host_work = os.path.join(self.base, "host", namespace, "ck")
        self.pvc = os.path.join(self.base, "pvc", namespace, "ck")
        self.dst_host = os.path.join(self.base, "dst", namespace, "ck")
        os.makedirs(self.sockdir, exist_ok=True)

    # -- workload processes ---------------------------------------------------

    def compile_cache_dir(self, which: str) -> str:
        """Per-process jit cache dirs ('src'/'dst' distinct on purpose:
        a warm destination cache must come from the checkpoint, not from
        sharing a directory)."""
        return os.path.join(self.base, f"jit-cache-{which}")

    def spawn(self, extra_env: dict | None = None, n_steps: int = 10,
              cache: str = "src") -> subprocess.Popen:
        import threading

        env = dict(os.environ, **{
            config.TPU_SOCKET_DIR.name: self.sockdir,
            config.TPU_COMPILE_CACHE.name: self.compile_cache_dir(cache),
            "N_STEPS": str(n_steps)}, **(extra_env or {}))
        proc = subprocess.Popen(
            [sys.executable, "-c", self.workload_src], stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=env, text=True, cwd=REPO,
        )
        # Drain stderr continuously: a chatty child must never block on a
        # full stderr pipe while we block on its stdout.
        chunks: list[str] = []

        def drain():
            for line in proc.stderr:
                chunks.append(line)

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        proc._grit_stderr = (t, chunks)  # type: ignore[attr-defined]
        return proc

    @staticmethod
    def _fail_exited(proc: subprocess.Popen, wanted: str) -> None:
        # Kill first: the child may still be alive (e.g. an unexpected line
        # rather than an exit) and the drain thread only finishes at EOF.
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        err = ""
        drain = getattr(proc, "_grit_stderr", None)
        if drain is not None:
            t, chunks = drain
            t.join(timeout=5.0)
            err = "".join(chunks)
        raise WorkloadExited(
            f"workload exited (rc={proc.poll()}) before {wanted}; stderr:\n{err}"
        )

    def wait_ready(self, proc: subprocess.Popen) -> None:
        line = proc.stdout.readline()
        if line.strip() != "READY":
            self._fail_exited(proc, "READY")

    def wait_until_step(self, proc: subprocess.Popen, step: int) -> None:
        while True:
            line = proc.stdout.readline()
            if not line:  # EOF: the workload died — surface its stderr
                self._fail_exited(proc, f"step {step}")
            m = re.match(r"STEP (\d+)", line)
            if m and int(m.group(1)) >= step:
                return

    def wait_restored_first_step(self, proc: subprocess.Popen,
                                 timeout: float | None = None) -> int:
        """Block until the restored process prints its first post-restore
        STEP; returns the restore cut step."""
        return self.wait_restored_first_step_timed(proc, timeout)[0]

    def wait_restored_first_step_timed(
        self, proc: subprocess.Popen, timeout: float | None = None
    ) -> tuple[int, float, float]:
        """Like :meth:`wait_restored_first_step`, but also returns wall
        timestamps ``(cut_step, t_restored, t_first_step)``: RESTORED
        marks state fully loaded (machinery done), the first STEP marks
        one post-restore step computed (workload compute) — the split a
        blackout report needs on hosts where a step is expensive.

        ``timeout`` bounds the whole wait: a workload that silently
        failed to restore (no RESTORED line) would otherwise grind
        through its entire step budget before EOF ends the read loop —
        on a benchmark host that is hours, not minutes.

        The wait is the process's LAST stdout reader (callers kill the
        workload right after), so a pump thread takes sole ownership of
        the stream — select() on the buffered text wrapper would miss
        lines already decoded into its buffer."""
        import queue
        import threading
        import time

        lines: "queue.Queue[str | None]" = queue.Queue()

        def pump() -> None:
            for line in proc.stdout:
                lines.put(line)
            lines.put(None)  # EOF marker

        threading.Thread(target=pump, daemon=True).start()
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        restored_at = None
        t_restored = 0.0
        while True:
            if deadline is not None:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self._fail_exited(
                        proc, f"RESTORED + first STEP within {timeout}s")
                wait = min(remaining, 5.0)
            else:
                wait = 5.0
            try:
                line = lines.get(timeout=wait)
            except queue.Empty:
                continue
            if line is None:
                self._fail_exited(proc, "RESTORED + first STEP")
            if line.startswith("RESTORED"):
                restored_at = int(line.split()[1])
                t_restored = time.perf_counter()
            if line.startswith("STEP") and restored_at is not None:
                return restored_at, t_restored, time.perf_counter()

    # -- source node ----------------------------------------------------------

    def make_source_runtime(self, workload_pid: int) -> FakeRuntime:
        runtime = FakeRuntime()
        runtime.add_sandbox(Sandbox(id="sb1", pod_name=self.pod,
                                    pod_namespace=self.namespace, pod_uid="uid1"))
        runtime.add_container(
            Container(id="c1", sandbox_id="sb1", name="main",
                      spec=OciSpec(image="img")),
            process=SimProcess(), running=True,
        )
        # the fake runtime assigns synthetic pids; point the task at the real
        # workload process so the device hook reaches its agentlet
        runtime.tasks["c1"].pid = workload_pid
        return runtime

    def _ckpt_opts(self, *, leave_running: bool = False,
                   pre_copy: bool = False,
                   migration_path: str = "") -> CheckpointOptions:
        return CheckpointOptions(
            pod_name=self.pod, pod_namespace=self.namespace,
            pod_uid="uid1", work_dir=self.host_work, dst_dir=self.pvc,
            kubelet_log_root=os.path.join(self.base, "logs"),
            leave_running=leave_running,
            pre_copy=pre_copy,
            migration_path=migration_path,
        )

    def precopy(self, runtime: FakeRuntime) -> dict:
        """Live pre-copy phase (runs OUTSIDE the blackout — the workload
        keeps training): the convergence loop's full dump + delta rounds
        + uploads. Returns the shipped capture for :meth:`checkpoint`
        ``preshipped``; per-round evidence (rounds, round_deltas,
        degraded) lands in :attr:`last_precopy_info`."""
        os.environ[config.TPU_SOCKET_DIR.name] = self.sockdir
        self.last_precopy_info: dict = {}
        try:
            return run_precopy_phase(
                runtime, self._ckpt_opts(pre_copy=True),
                device_hook=AutoDeviceHook(),
                info=self.last_precopy_info,
            )
        finally:
            os.environ.pop(config.TPU_SOCKET_DIR.name, None)

    def standby(self, runtime: FakeRuntime, *, fire=None, stop=None,
                max_rounds=None, migration_path: str = ""):
        """Preemption-armed standby over the live workload: round-0 full
        dump + governed delta rounds keep the PVC base warm until
        ``fire`` delivers a reason (then only the final delta + blackout
        runs) or ``stop``/``max_rounds`` disarms. Arm/fire evidence
        lands in :attr:`last_standby_info`."""
        from grit_tpu.agent.standby import run_standby_checkpoint

        os.environ[config.TPU_SOCKET_DIR.name] = self.sockdir
        self.last_standby_info: dict = {}
        try:
            return run_standby_checkpoint(
                runtime,
                self._ckpt_opts(pre_copy=True,
                                migration_path=migration_path),
                device_hook=AutoDeviceHook(),
                fire=fire, info=self.last_standby_info, stop=stop,
                max_rounds=max_rounds,
            )
        finally:
            os.environ.pop(config.TPU_SOCKET_DIR.name, None)

    def checkpoint(
        self, runtime: FakeRuntime, *, leave_running: bool = False,
        pre_copy: bool = False, preshipped: dict | None = None,
        migration_path: str = "",
    ) -> None:
        os.environ[config.TPU_SOCKET_DIR.name] = self.sockdir
        try:
            run_checkpoint(
                runtime,
                self._ckpt_opts(leave_running=leave_running,
                                pre_copy=pre_copy,
                                migration_path=migration_path),
                device_hook=AutoDeviceHook(),
                preshipped=preshipped,
            )
        finally:
            os.environ.pop(config.TPU_SOCKET_DIR.name, None)

    def abort(self, runtime: FakeRuntime, stage: bool = True):
        """Abort a failed migration leg: resume the (possibly quiesced)
        source workload from live HBM state, clear the dead attempt's
        partial dump, and poison-and-clear the destination stage dir —
        the node-side work the manager's ``--action abort`` Job performs.
        Returns the :class:`~grit_tpu.agent.abort.AbortOutcome`."""
        os.environ[config.TPU_SOCKET_DIR.name] = self.sockdir
        try:
            return run_abort(
                runtime,
                AbortOptions(
                    pod_name=self.pod, pod_namespace=self.namespace,
                    pod_uid="uid1", work_dir=self.host_work,
                    stage_dir=self.dst_host if stage else "",
                ),
                device_hook=AutoDeviceHook(),
            )
        finally:
            os.environ.pop(config.TPU_SOCKET_DIR.name, None)

    # -- destination node -----------------------------------------------------

    def prestage(self) -> dict:
        """Destination half of pre-copy: download whatever the live pass
        landed on the PVC while the source still runs (no sentinel).
        Returns the capture for :meth:`stage` ``prestaged``."""
        return run_prestage(
            RestoreOptions(src_dir=self.pvc, dst_dir=self.dst_host))

    def stage(self, prestaged: dict | None = None) -> None:
        run_restore(RestoreOptions(src_dir=self.pvc, dst_dir=self.dst_host),
                    prestaged=prestaged)

    def stage_streamed(self, prestaged: dict | None = None) -> StreamedRestore:
        """Chunk-streamed stage: returns once the metadata priority set is
        down (sentinel dropped — the restored pod may spawn NOW and its
        restore pipeline consumes arrays through the stage journal while
        the bulk data is still crossing). Callers must ``.wait()`` the
        handle before tearing the harness down."""
        return run_restore_streamed(
            RestoreOptions(src_dir=self.pvc, dst_dir=self.dst_host),
            prestaged=prestaged)

    def stage_wire(self, prestage: bool = False) -> WireRestore:
        """Wire-mode destination: start the receiver BEFORE the source
        checkpoint (its endpoint is published into the PVC work dir for
        the checkpoint agent to dial); pair with
        ``checkpoint(migration_path="wire")``, then ``.wait()`` the
        handle — the sentinel drops at the verified commit, with every
        checkpoint byte having crossed exactly one hop. ``prestage``
        pulls the PVC's current content (a pre-copy base) first."""
        return run_restore_wire(
            RestoreOptions(src_dir=self.pvc, dst_dir=self.dst_host),
            prestage=prestage)

    def shim_restore_spec(self) -> OciSpec:
        """Create the replacement container through the shim; returns the
        rewritten OCI spec (carrying RESTORE_ENV) for the restored spawn."""
        from grit_tpu.runtime.shim import ShimTaskService

        dst_runtime = FakeRuntime()
        dst_runtime.add_sandbox(Sandbox(id="sb2", pod_name=self.pod,
                                        pod_namespace=self.namespace,
                                        pod_uid="uid2"))
        shim = ShimTaskService(dst_runtime)
        spec = OciSpec(image="img", annotations={
            CHECKPOINT_DATA_PATH_ANNOTATION: self.dst_host,
            "io.kubernetes.cri.container-type": "container",
        })
        entry = shim.create("sb2", "c2", "main", spec)
        if not entry.restore_from:
            raise RuntimeError("shim did not rewrite create into restore")
        return spec

    def restore_env(self, spec: OciSpec) -> dict:
        return {RESTORE_ENV: spec.env[RESTORE_ENV]}


# Per-host slice workload: a rank-seeded deterministic trainer (distinct
# loss sequence per host, same sequence per rank in any process) whose
# agentlet carries a SliceQuiesceGate over a FileRendezvous — the
# cross-process transport N simulated hosts on one node share. A small
# rank-proportional sleep desynchronizes the hosts' step counters so the
# gate's run-forward rule is actually exercised.
SLICE_WORKLOAD = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from functools import partial
    from grit_tpu.models import mnist
    from grit_tpu.train import Trainer, TrainerConfig
    from grit_tpu.device.agentlet import Agentlet
    from grit_tpu.parallel.coordination import (
        FileRendezvous, SliceCoordinator, SliceQuiesceGate,
    )

    rank = int(os.environ["SLICE_RANK"])
    world = int(os.environ["SLICE_WORLD"])
    rdv = FileRendezvous(os.environ["SLICE_RDV_DIR"], rank, world)
    coord = SliceCoordinator(rdv, process_index=rank, process_count=world)
    gate = SliceQuiesceGate(coord)

    cfg = mnist.MnistConfig(hidden_dim=16)
    tr = Trainer(
        loss_fn=partial(mnist.loss_fn, cfg),
        init_params=partial(mnist.init_params, cfg),
        batch_fn=lambda rng: mnist.synthetic_batch(cfg, rng, 16),
        cfg=TrainerConfig(seed=1000 + rank),
    )
    restored = tr.maybe_restore_from_env()
    if restored is not None:
        print(f"RESTORED {{restored}}", flush=True)
    agentlet = Agentlet(lambda: tr.state, step_fn=lambda: tr.step,
                        slice_gate=gate).start()
    print("READY", flush=True)
    n_steps = int(os.environ.get("N_STEPS", "10"))
    while tr.step < n_steps:
        loss = float(tr.train_step()["loss"])
        print(f"STEP {{tr.step}} {{loss!r}}", flush=True)
        time.sleep(0.01 * rank)  # desync hosts: the cut must run-forward
        agentlet.checkpoint_point()
    print("DONE", flush=True)
""").format(repo=REPO)


class SliceHarness:
    """N simulated hosts of one slice migration over a shared base dir.

    Layout::

        <base>/socks                  agentlet sockets (per-pid: shared)
        <base>/rdv                    FileRendezvous dir (quiesce barrier)
        <base>/pvc/<ns>/<ck>          SHARED PVC work dir (gang ledger at
                                      .grit-slice/; per-host payload under
                                      host-<k>/)
        <base>/h<k>/host/<ns>/<ck>    host k's source work dir
        <base>/h<k>/dst/<ns>/<ck>     host k's destination staging dir

    Workloads are real OS processes (one per host, rank-seeded
    deterministic losses); the per-host agent legs run through
    :func:`grit_tpu.agent.slicerole.run_slice_checkpoint` /
    ``run_slice_restore`` — in-process for happy paths, as driver
    subprocesses in the chaos tests (a ``kill`` fault needs a process
    to die).
    """

    def __init__(self, base_dir: str, hosts: int = 2, pod: str = "train",
                 namespace: str = "ns1") -> None:
        self.base = str(base_dir)
        self.hosts = hosts
        self.pod = pod
        self.namespace = namespace
        self.sockdir = os.path.join(self.base, "socks")
        self.rdv_dir = os.path.join(self.base, "rdv")
        self.shared_pvc = os.path.join(self.base, "pvc", namespace, "ck")
        os.makedirs(self.sockdir, exist_ok=True)
        os.makedirs(self.rdv_dir, exist_ok=True)

    # -- per-host paths -------------------------------------------------------

    def work_dir(self, k: int) -> str:
        return os.path.join(self.base, f"h{k}", "host", self.namespace, "ck")

    def dst_host(self, k: int) -> str:
        return os.path.join(self.base, f"h{k}", "dst", self.namespace, "ck")

    def pvc_dir(self, k: int) -> str:
        """Host k's payload subdir of the SHARED PVC work dir (the gang
        ledger lives at the shared root)."""
        return os.path.join(self.shared_pvc, f"host-{k:04d}")

    def role(self, k: int):
        from grit_tpu.agent.slicerole import SliceRole

        return SliceRole(ordinal=k, hosts=self.hosts)

    # -- workloads ------------------------------------------------------------

    def spawn(self, k: int, n_steps: int = 1000,
              extra_env: dict | None = None) -> subprocess.Popen:
        import threading

        env = dict(os.environ)
        env.update({
            config.TPU_SOCKET_DIR.name: self.sockdir,
            "SLICE_RANK": str(k),
            "SLICE_WORLD": str(self.hosts),
            "SLICE_RDV_DIR": self.rdv_dir,
            "N_STEPS": str(n_steps)})
        env.update(extra_env or {})  # caller overrides win (ref runs)
        proc = subprocess.Popen(
            [sys.executable, "-c", SLICE_WORKLOAD],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=env, text=True, cwd=REPO,
        )
        chunks: list[str] = []

        def drain():
            for line in proc.stderr:
                chunks.append(line)

        t = threading.Thread(target=drain, daemon=True)
        t.start()
        proc._grit_stderr = (t, chunks)  # type: ignore[attr-defined]
        return proc

    # -- agent legs -----------------------------------------------------------

    def make_source_runtime(self, k: int, workload_pid: int) -> FakeRuntime:
        runtime = FakeRuntime()
        runtime.add_sandbox(Sandbox(
            id=f"sb{k}", pod_name=f"{self.pod}-{k}",
            pod_namespace=self.namespace, pod_uid=f"uid{k}"))
        runtime.add_container(
            Container(id=f"c{k}", sandbox_id=f"sb{k}", name="main",
                      spec=OciSpec(image="img")),
            process=SimProcess(), running=True,
        )
        runtime.tasks[f"c{k}"].pid = workload_pid
        return runtime

    def ckpt_opts(self, k: int, *, leave_running: bool = False,
                  migration_path: str = "") -> CheckpointOptions:
        return CheckpointOptions(
            pod_name=f"{self.pod}-{k}", pod_namespace=self.namespace,
            pod_uid=f"uid{k}", work_dir=self.work_dir(k),
            dst_dir=self.pvc_dir(k),
            kubelet_log_root=os.path.join(self.base, "logs"),
            leave_running=leave_running,
            migration_path=migration_path,
        )

    def restore_opts(self, k: int) -> RestoreOptions:
        return RestoreOptions(src_dir=self.pvc_dir(k),
                              dst_dir=self.dst_host(k))

    def checkpoint_host(self, k: int, runtime: FakeRuntime,
                        **opt_kwargs) -> None:
        """One host's gang checkpoint leg, in-process (the chaos tests
        drive subprocess twins of this so a kill fault has a process to
        die in)."""
        from grit_tpu.agent.slicerole import run_slice_checkpoint

        os.environ[config.TPU_SOCKET_DIR.name] = self.sockdir
        os.environ[config.SLICE_HOSTS.name] = str(self.hosts)
        os.environ[config.SLICE_ORDINAL.name] = str(k)
        try:
            run_slice_checkpoint(
                runtime, self.ckpt_opts(k, **opt_kwargs),
                role=self.role(k), device_hook=AutoDeviceHook())
        finally:
            os.environ.pop(config.TPU_SOCKET_DIR.name, None)
            os.environ.pop(config.SLICE_HOSTS.name, None)
            os.environ.pop(config.SLICE_ORDINAL.name, None)

    def restore_host(self, k: int,
                     ordinal_mapping: dict[int, int] | None = None):
        from grit_tpu.agent.slicerole import run_slice_restore

        return run_slice_restore(self.restore_opts(k), role=self.role(k),
                                 ordinal_mapping=ordinal_mapping)

    def abort_host(self, k: int, runtime: FakeRuntime):
        """Host k's slice abort: resume its source from live HBM state
        and record the gang ledger's ABORT (first writer wins)."""
        os.environ[config.TPU_SOCKET_DIR.name] = self.sockdir
        os.environ[config.SLICE_HOSTS.name] = str(self.hosts)
        os.environ[config.SLICE_ORDINAL.name] = str(k)
        try:
            return run_abort(
                runtime,
                AbortOptions(
                    pod_name=f"{self.pod}-{k}",
                    pod_namespace=self.namespace, pod_uid=f"uid{k}",
                    work_dir=self.work_dir(k),
                    stage_dir=self.dst_host(k),
                    gang_shared_dir=self.shared_pvc,
                ),
                device_hook=AutoDeviceHook(),
            )
        finally:
            os.environ.pop(config.TPU_SOCKET_DIR.name, None)
            os.environ.pop(config.SLICE_HOSTS.name, None)
            os.environ.pop(config.SLICE_ORDINAL.name, None)
