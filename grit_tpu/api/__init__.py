"""User-facing API: Checkpoint/Restore resource types, phases and constants.

Behavioral parity with reference ``pkg/apis/v1alpha1/`` (checkpoint.go,
restore.go, constants.go, register.go).
"""

from grit_tpu.api.constants import (  # noqa: F401
    API_GROUP,
    API_VERSION,
    CHECKPOINT_DATA_PATH_ANNOTATION,
    CREATION_MODE_ANNOTATION,
    GRIT_AGENT_LABEL,
    GRIT_AGENT_NAME,
    POD_SELECTED_ANNOTATION,
    POD_SPEC_HASH_ANNOTATION,
    RESTORE_NAME_ANNOTATION,
)
from grit_tpu.api.types import (  # noqa: F401
    Checkpoint,
    CheckpointPhase,
    CheckpointSpec,
    CheckpointStatus,
    Condition,
    Restore,
    RestorePhase,
    RestoreSpec,
    RestoreStatus,
)
