"""Central registry of every ``GRIT_*`` environment knob.

grit-tpu's cross-process contracts are strings: the manager stamps env
vars into agent Jobs, the shim injects them into restored pods, operators
export them into node daemonsets. Before this registry the same knob was
parsed at several call sites with independently-typed defaults — exactly
the silently-divergent-default class of bug CRIUgpu/PhoenixOS blame for
restore corruption. Now every knob is declared ONCE here (name, type,
default, doc) and read ONLY through it:

- ``config.WIRE_STREAMS.get()`` — typed read with the one shared policy
  for malformed values (log once, use the declared default — a typo
  degrades to shipped behavior, never a crash in a data-path leg; empty
  string means unset).
- ``config.JOB_NAME.name`` — the literal env name, for sites that stamp
  or compare env entries (Job specs, subprocess environments).

``tools/gritlint``'s **env-contract** rule enforces the funnel: any
``GRIT_*`` string literal or raw ``os.environ`` read of one elsewhere in
``grit_tpu/`` fails the build, as does drift between this registry and
the generated ``docs/config-reference.md`` table
(``python -m tools.gritlint --write-refs`` regenerates it).

This module must stay import-light (stdlib only): the lint engine, the
agent's argparse layer, and the native loader all import it before (or
without) jax existing.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

log = logging.getLogger(__name__)

_TYPES = ("str", "int", "float", "bool")


@dataclass(frozen=True)
class Knob:
    """One declared environment knob. Immutable; reads go to os.environ
    on every call (knobs are control-plane-settable per Job, and tests
    flip them constantly — caching would invert the contract)."""

    name: str
    default: str | int | float | bool
    type: str
    doc: str
    #: Who reads this knob: "python" (the grit_tpu tree — the
    #: env-contract lint requires a live call site), "native" (the CRIU
    #: plugin / shim read it by literal name in C), or "tests" (test-lane
    #: infrastructure like the chaos seed). Declared here regardless so
    #: the contract has one home and the reference table covers them.
    scope: str = "python"

    def raw(self) -> str | None:
        """The raw env value, or None when unset. Empty string counts as
        unset — every pre-registry call site treated it that way
        (``os.environ.get(X, "") or default`` truthiness checks)."""
        v = os.environ.get(self.name)
        return v if v else None

    def get(self) -> "str | int | float | bool":
        """Typed value: parsed env when set and well-formed, else the
        declared default. Malformed values log a warning and fall back —
        one policy for the whole tree (previously ad-hoc try/except
        blocks per site, some of which crashed on a typo)."""
        raw = self.raw()
        if raw is None:
            return self.default
        if self.type == "str":
            return raw
        if self.type == "bool":
            # The tree's convention: "0" disables, anything else enables
            # (GRIT_RESTORE_PIPELINE=0, GRIT_TPU_NATIVE=0).
            return raw != "0"
        try:
            return int(raw) if self.type == "int" else float(raw)
        except ValueError:
            log.warning("%s=%r is not a valid %s; using default %r",
                        self.name, raw, self.type, self.default)
            return self.default


#: name → Knob, in declaration order (the reference table preserves it).
REGISTRY: dict[str, Knob] = {}


def _declare(name: str, default: "str | int | float | bool", type_: str,
             doc: str, scope: str = "python") -> Knob:
    if type_ not in _TYPES:
        raise ValueError(f"knob {name}: unknown type {type_!r}")
    if name in REGISTRY:
        raise ValueError(f"knob {name} declared twice")
    if not name.startswith("GRIT_"):
        raise ValueError(f"knob {name}: registry is for GRIT_* names")
    if scope not in ("python", "native", "tests"):
        raise ValueError(f"knob {name}: unknown scope {scope!r}")
    knob = Knob(name=name, default=default, type=type_, doc=doc, scope=scope)
    REGISTRY[name] = knob
    return knob


def _str(name: str, default: str, doc: str) -> Knob:
    return _declare(name, default, "str", doc)


def _int(name: str, default: int, doc: str) -> Knob:
    return _declare(name, default, "int", doc)


def _float(name: str, default: float, doc: str) -> Knob:
    return _declare(name, default, "float", doc)


def _bool(name: str, default: bool, doc: str) -> Knob:
    return _declare(name, default, "bool", doc)


# -- migration data path ------------------------------------------------------

MIGRATION_PATH = _str(
    "GRIT_MIGRATION_PATH", "pvc",
    "Checkpoint data path: 'pvc' (double hop through the checkpoint PVC) "
    "or 'wire' (direct source-to-destination stream, PVC demoted to an "
    "async durability tee). Propagated into both agent Jobs from the "
    "grit.dev/migration-path CR annotation.")
WIRE_HOST = _str(
    "GRIT_WIRE_HOST", "",
    "Explicit bind/publish address for the wire receiver. Unset: listen "
    "on all interfaces and publish the node's primary address.")
WIRE_STREAMS = _int(
    "GRIT_WIRE_STREAMS", 2,
    "Parallel TCP streams the wire sender dials to the receiver.")
WIRE_ENDPOINT_WAIT_S = _float(
    "GRIT_WIRE_ENDPOINT_WAIT_S", 2.0,
    "How long the source agent waits for the receiver's endpoint file "
    "to appear in the PVC work dir before falling back to the PVC path.")
WIRE_COMMIT_TIMEOUT_S = _float(
    "GRIT_WIRE_COMMIT_TIMEOUT_S", 600.0,
    "Deadline for the destination's commit ack after the final frame.")
WIRE_RESTORE_TIMEOUT_S = _float(
    "GRIT_WIRE_RESTORE_TIMEOUT_S", 900.0,
    "Ceiling on a wire restore session (peer never dials / dies "
    "mid-stream) before the loud WireError -> PVC fallback.")
WIRE_ABORT_GRACE_S = _float(
    "GRIT_WIRE_ABORT_GRACE_S", 10.0,
    "Grace after a pre-existing PVC tee marker before a never-dialed "
    "wire receiver aborts to the PVC path (sequenced agent Jobs).")
WIRE_TEE_WAIT_S = _float(
    "GRIT_WIRE_TEE_WAIT_S", 30.0,
    "How long a wire fallback waits for the source's durability-tee "
    "marker before staging whatever the PVC already holds.")
WIRE_FLUSH_TIMEOUT_S = _float(
    "GRIT_WIRE_FLUSH_TIMEOUT_S", 600.0,
    "Bound on draining the per-stream send queues at commit time; a "
    "consumer thread wedged past it fails the wire session loudly.")
WIRE_NATIVE = _bool(
    "GRIT_WIRE_NATIVE", True,
    "Native (libgritio) wire data plane: payload bytes move through the "
    "C ring-buffer send worker / sendfile(2) / native frame decode + "
    "pwrite instead of the Python frame loop (headers, codec decisions, "
    "journal and commit handshake stay in Python; the wire format is "
    "identical, so mixed native/Python ends interoperate). =0 forces "
    "the pure-Python loop; a missing .so logs the degrade loudly and "
    "falls back.")
WIRE_IFACES = _str(
    "GRIT_WIRE_IFACES", "",
    "Comma-separated network interface names for multi-NIC striping: "
    "wire stream k is pinned (SO_BINDTODEVICE) to iface k mod N before "
    "it dials, so parallel streams saturate parallel NICs. Requires "
    "CAP_NET_RAW (the agent Job runs privileged); a refused pin logs "
    "loudly and the stream dials unpinned. Unset: no pinning.")
STAGE_STREAM_TIMEOUT_S = _float(
    "GRIT_STAGE_STREAM_TIMEOUT_S", 900.0,
    "Default deadline when joining the background streamed-stage "
    "transfer (StreamedRestore.wait).")
SNAPSHOT_CODEC = _str(
    "GRIT_SNAPSHOT_CODEC", "none",
    "Chunk codec for the snapshot transport path (wire frames and the "
    "PVC streaming tee's container format): 'none', 'zlib', or 'zstd' "
    "(degrades to zlib with a loud warning when the optional zstandard "
    "module is absent; unknown values degrade to none). Compression is "
    "adaptive per chunk — see GRIT_CODEC_MIN_RATIO.")
CODEC_WORKERS = _int(
    "GRIT_CODEC_WORKERS", -1,
    "Bounded codec worker-pool size (compress on the dump side, "
    "decompress + CRC verify on the receive side); -1 (unset) sizes "
    "from the host's cores.")
CODEC_MIN_RATIO = _float(
    "GRIT_CODEC_MIN_RATIO", 0.9,
    "Adaptive raw-ship threshold: a chunk whose sample compresses to "
    "MORE than this fraction of its raw size ships uncompressed (the "
    "codec must pay for itself on the wire).")
CODEC_SAMPLE_KB = _int(
    "GRIT_CODEC_SAMPLE_KB", 64,
    "KiB of each chunk's head that is sample-compressed to make the "
    "compress-vs-raw-ship decision.")
MIRROR_MAX_INFLIGHT_MB = _int(
    "GRIT_MIRROR_MAX_INFLIGHT_MB", 256,
    "Bound on in-flight BYTES queued between the HBM dump and its "
    "mirror/wire tee. Backpressure is by bytes, not item count — "
    "compressed chunks make item-count bounds meaningless for memory "
    "pressure.")
TPU_STAGE_TIMEOUT_S = _float(
    "GRIT_TPU_STAGE_TIMEOUT_S", 900.0,
    "How long any consumer of staged-in-flight data (restore pipeline "
    "chunk gates, wire eof/commit verification) waits for bytes that "
    "never arrive before failing loud.")

# -- iterative pre-copy convergence / post-copy restore -----------------------

PRECOPY_MAX_ROUNDS = _int(
    "GRIT_PRECOPY_MAX_ROUNDS", 3,
    "Ceiling on live pre-copy rounds (1 full pass + N-1 delta rounds). "
    "1 restores the single-live-pass behavior; the loop stops earlier "
    "when a round's delta stops shrinking (GRIT_PRECOPY_CONVERGENCE_"
    "RATIO) or the dirty rate reaches the observed upload rate.")
PRECOPY_CONVERGENCE_RATIO = _float(
    "GRIT_PRECOPY_CONVERGENCE_RATIO", 0.8,
    "A pre-copy round must shrink to strictly below this fraction of the "
    "previous round's delta bytes for another round to run; otherwise "
    "the loop enters blackout with what it has.")
PRECOPY_ROUND_DEADLINE_S = _float(
    "GRIT_PRECOPY_ROUND_DEADLINE_S", 300.0,
    "Wall ceiling on one pre-copy round (delta dump + flatten + upload); "
    "an overrunning round is the loop's last — blackout proceeds with "
    "the rounds already shipped, and the watchdog classifies any phase "
    "overrun as retriable (the agent renews its lease every round).")
RESTORE_POSTCOPY = _bool(
    "GRIT_RESTORE_POSTCOPY", False,
    "Post-copy (lazy) restore: the restored workload resumes once the "
    "manifest + hot (small) arrays are placed, and the cold bulk is "
    "placed in the background in readiness order — first touch blocks "
    "per-array on the stage waterline instead of on the whole bulk. "
    "=0 keeps the blocking restore; serial and pipelined paths remain.")
RESTORE_POSTCOPY_HOT_MB = _float(
    "GRIT_RESTORE_POSTCOPY_HOT_MB", 8.0,
    "Per-array hot-set threshold for post-copy restore: arrays at or "
    "below this many MB (scalars, RNG keys, norms) are placed before "
    "the workload resumes; larger arrays fault in through the post-copy "
    "tail. 0 sends every array to the tail.")

# -- preemption-armed standby (always-warm pre-copy) --------------------------

STANDBY_MIN_INTERVAL_S = _float(
    "GRIT_STANDBY_MIN_INTERVAL_S", 15.0,
    "Floor of the standby governor's round cadence: the shortest gap "
    "between two governed delta probes (each is a momentary quiesce). "
    "A dirty burst tightens the cadence back down to this floor within "
    "one interval.")
STANDBY_MAX_INTERVAL_S = _float(
    "GRIT_STANDBY_MAX_INTERVAL_S", 300.0,
    "Ceiling of the governor's exponential backoff on quiet workloads: "
    "a standby whose probes keep finding nothing dirty converges to one "
    "probe per this many seconds.")
STANDBY_BACKOFF = _float(
    "GRIT_STANDBY_BACKOFF", 2.0,
    "Backoff multiplier the standby governor applies to its interval "
    "after a round too small to ship (clamped to >= 1.0 at the read "
    "site; the interval stays within [GRIT_STANDBY_MIN_INTERVAL_S, "
    "GRIT_STANDBY_MAX_INTERVAL_S]).")
STANDBY_MIN_DELTA_MB = _float(
    "GRIT_STANDBY_MIN_DELTA_MB", 1.0,
    "Smallest delta worth shipping between governed rounds: a probe "
    "that finds fewer dirty megabytes than this is discarded (the "
    "bytes stay in the final-delta budget, which carries them for "
    "free) and the governor backs off. 0 ships every nonzero delta.")
STANDBY_FIRE_POLL_S = _float(
    "GRIT_STANDBY_FIRE_POLL_S", 1.0,
    "How often an armed standby agent polls its fire signals (the "
    ".grit-fire file in the work/PVC dirs and the grit.dev/fire Job "
    "annotation) while idling between governed rounds. The notice-to-"
    "blackout latency floor.")
STANDBY_STALE_S = _float(
    "GRIT_STANDBY_STALE_S", 180.0,
    "Manager watchdog threshold on a FROZEN standby governor: the "
    "agent's lease still beats but the standby tick timestamp in the "
    "progress snapshot has not moved for this long — classifies "
    "retriable (StandbyStale) and re-arms a fresh agent. A healthy "
    "idle-armed standby ticks on every fire poll, so long governed "
    "intervals never trip this. 0 disables the check.")
STANDBY_REBASE_FACTOR = _float(
    "GRIT_STANDBY_REBASE_FACTOR", 2.0,
    "Disk-bloat bound on the rolling standby base: when the base dir's "
    "physical data bytes exceed this multiple of the state's logical "
    "size (superseded chunk bytes accumulated across unbounded flatten "
    "rounds), the next shipped round is a fresh full dump that rebases "
    "instead of a delta. 0 disables rebasing.")

# -- gang slice migration (multi-host) ----------------------------------------

SLICE_HOSTS = _int(
    "GRIT_SLICE_HOSTS", 0,
    "Host count of the slice this agent leg belongs to. 0/1 = the "
    "single-host flow (everything before gang migration). >1 turns the "
    "agent into one replica of a gang: its dump/restore leg coordinates "
    "through the shared .grit-slice ledger in the PVC work dir "
    "(all-or-nothing gang commit, slice-wide abort). The manager stamps "
    "it into every per-host agent Job from CheckpointSpec.sliceHosts.")
SLICE_ORDINAL = _int(
    "GRIT_SLICE_ORDINAL", 0,
    "This agent leg's host ordinal within the slice (0-based, < "
    "GRIT_SLICE_HOSTS). Names the host's ledger markers, the per-host "
    "flight role (source-h0002) and the progress snapshot's ord field.")
SLICE_BARRIER_TIMEOUT_S = _float(
    "GRIT_SLICE_BARRIER_TIMEOUT_S", 120.0,
    "Bound on the cross-host quiesce barrier: how long one host waits "
    "at the agreed cut step for every other host to arrive before the "
    "barrier fails LOUDLY (the workload keeps training, the quiesce "
    "times out, and the gang aborts) instead of parking a partial "
    "slice against a host that never comes.")
SLICE_COMMIT_TIMEOUT_S = _float(
    "GRIT_SLICE_COMMIT_TIMEOUT_S", 900.0,
    "Bound on the gang-commit wait: how long a prepared destination "
    "parks for the slice-wide commit record before writing ABORT "
    "itself and failing loudly — a gang that cannot commit must abort "
    "everywhere, never hold some hosts resumed and others parked.")
SLICE_POLL_S = _float(
    "GRIT_SLICE_POLL_S", 0.2,
    "Poll period of the gang ledger's marker/commit waits and the "
    "file rendezvous barrier (shared-filesystem coordination paths).")
SLICE_NONCE = _str(
    "GRIT_SLICE_NONCE", "",
    "Attempt namespace for the gang's rendezvous names (the manager "
    "stamps the CR's grit.dev/attempt count into every per-host agent "
    "Job). A retried gang must never meet a failed attempt's leftover "
    "barrier arrivals; scoping every rendezvous name by this nonce "
    "guarantees it. Empty = attempt 0.")

# -- fleet migration scheduler (MigrationPlan) --------------------------------

FLEET_MAX_CONCURRENT = _int(
    "GRIT_FLEET_MAX_CONCURRENT", 2,
    "Default global ceiling on member migrations a MigrationPlan runs "
    "concurrently, when the plan's spec.budget.maxConcurrent is unset. "
    "Clamped to >= 1 at the read site.")
FLEET_LINK_BUDGET_MBPS = _float(
    "GRIT_FLEET_LINK_BUDGET_MBPS", 0.0,
    "Default per source->destination link bandwidth budget (MB/s) when "
    "the plan's spec.budget.linkBandwidthBps is unset. 0 = unlimited.")
FLEET_BUDGET_MBPS = _float(
    "GRIT_FLEET_BUDGET_MBPS", 0.0,
    "Default fleet-wide bandwidth budget (MB/s) across every link when "
    "the plan's spec.budget.fleetBandwidthBps is unset. 0 = unlimited.")
FLEET_POLL_S = _float(
    "GRIT_FLEET_POLL_S", 5.0,
    "MigrationPlan reconcile poll cadence while member migrations run "
    "(budget utilization refresh, wave admission, retry folding).")
FLEET_MAX_RETRIES = _int(
    "GRIT_FLEET_MAX_RETRIES", 1,
    "Default plan-level retries per pod (fresh member Checkpoint after "
    "the previous one aborted-to-source terminally) when the plan's "
    "spec.maxRetriesPerPod is unset. 0 = report the first terminal "
    "failure in status.pods[] without retrying.")
FLEET_BURST_S = _float(
    "GRIT_FLEET_BURST_S", 5.0,
    "Burst window of the fleet bandwidth token buckets: a link's bucket "
    "holds at most budget x this many seconds of tokens (the ceiling), "
    "so an idle link cannot bank unlimited credit and then blow the "
    "instantaneous budget when the wave lands.")
FLEET_SHAPE_WINDOW_S = _float(
    "GRIT_FLEET_SHAPE_WINDOW_S", 2.0,
    "Byte-shaping horizon: a member's link-budget share (bytes/s) is "
    "actuated as GRIT_MIRROR_MAX_INFLIGHT_MB = share x this many "
    "seconds — the in-flight bound that keeps its sustained rate near "
    "the share without starving the dump mirror.")
FLEET_HBM_PER_CHIP_GB = _float(
    "GRIT_FLEET_HBM_PER_CHIP_GB", 16.0,
    "HBM demand assumed per google.com/tpu chip when a member pod "
    "declares no grit.dev/hbm-gb annotation (v5e-class default), for "
    "the bin-packing destination chooser's capacity accounting.")
FLEET_STATUS_DIR = _str(
    "GRIT_FLEET_STATUS_DIR", "",
    "Directory where the plan controller atomically publishes one "
    ".grit-fleet-<ns>-<plan>.json snapshot per reconcile (member "
    "states + folded progress + budget utilization) — the feed "
    "`gritscope watch --plan` renders the live fleet view from. "
    "Unset: no snapshot files.")

# -- serving snapshot fan-out (RestoreSet) ------------------------------------

SERVE_DRAIN_MODE = _str(
    "GRIT_SERVE_DRAIN_MODE", "serialize",
    "Request-drain policy the serving agentlet applies when a quiesce "
    "lands: 'serialize' (default) parks at the next batch boundary and "
    "ships in-flight slots' KV/position state inside the snapshot; "
    "'drain' keeps decoding admitted requests to completion (EOS/"
    "length) before parking — bounded by GRIT_SERVE_DRAIN_TIMEOUT_S. "
    "Unknown values degrade to 'serialize' loudly.")
SERVE_DRAIN_TIMEOUT_S = _float(
    "GRIT_SERVE_DRAIN_TIMEOUT_S", 30.0,
    "Ceiling on the 'drain' policy's run-to-completion window. Expiry "
    "raises ServingDrainTimeout out of the serving loop — a drain that "
    "cannot finish must fail the migration attempt loudly, never "
    "silently serialize or park a half-drained batch.")
SERVE_MAX_CLONES = _int(
    "GRIT_SERVE_MAX_CLONES", 32,
    "Admission ceiling on RestoreSet spec.replicas (validating "
    "webhook): one operator typo must not fan a snapshot out into "
    "hundreds of restore legs against one source PVC.")
SERVE_POLL_S = _float(
    "GRIT_SERVE_POLL_S", 5.0,
    "RestoreSet reconcile poll cadence while clone restores run "
    "(status.replicas[] fan-in, readyReplicas gate, progress fold).")
SERVE_STATUS_DIR = _str(
    "GRIT_SERVE_STATUS_DIR", "",
    "Directory where the RestoreSet controller atomically publishes "
    "one .grit-restoreset-<ns>-<name>.json snapshot per reconcile "
    "(per-clone states + folded progress) — the feed `gritscope watch "
    "--restoreset` renders the live fan-out view from. Unset: no "
    "snapshot files.")
CLONE_ORDINAL = _int(
    "GRIT_CLONE_ORDINAL", -1,
    "This restore leg's clone ordinal within a RestoreSet fan-out "
    "(from the Restore CR's grit.dev/clone-ordinal annotation, stamped "
    "into the agent Job env). Every clone derives the SAME progress uid "
    "from the shared snapshot name, so the ordinal rides the progress "
    "snapshot as 'clone' — what lets `gritscope watch --restoreset` "
    "key live per-clone files apart. -1: not a clone.")

# -- leased phases / watchdog -------------------------------------------------

HEARTBEAT_PERIOD_S = _float(
    "GRIT_HEARTBEAT_PERIOD_S", 15.0,
    "Agent heartbeat-lease renewal cadence (grit.dev/heartbeat).")
HEARTBEAT_FILE = _str(
    "GRIT_HEARTBEAT_FILE", "",
    "File-renewer target for the heartbeat lease (harness and "
    "no-apiserver nodes). Outranks Job-annotation renewal when set.")
JOB_NAME = _str(
    "GRIT_JOB_NAME", "",
    "The agent Job's own name, stamped into its env by the "
    "AgentManager; enables Job-annotation lease renewal.")
JOB_NAMESPACE = _str(
    "GRIT_JOB_NAMESPACE", "default",
    "Namespace of the agent Job for lease renewal.")
LEASE_TIMEOUT_S = _float(
    "GRIT_LEASE_TIMEOUT_S", 120.0,
    "Heartbeat staleness threshold after which the manager watchdog "
    "fails the attempt over to the retry/abort machinery.")
PHASE_DEADLINE_S = _float(
    "GRIT_PHASE_DEADLINE_S", 900.0,
    "Ceiling on one migration phase before the watchdog declares an "
    "overrun (bounds Jobs that never produced a first heartbeat).")
AGENT_MAX_ATTEMPTS = _int(
    "GRIT_AGENT_MAX_ATTEMPTS", 3,
    "Bounded agent-Job re-creations per CR (grit.dev/attempt) before "
    "the abort machine takes over. Clamped to >= 1 at the read site.")
RETRY_BACKOFF_S = _float(
    "GRIT_RETRY_BACKOFF_S", 2.0,
    "Base of the capped-exponential agent-Job retry backoff.")
RETRY_BACKOFF_CAP_S = _float(
    "GRIT_RETRY_BACKOFF_CAP_S", 60.0,
    "Cap of the agent-Job retry backoff.")

# -- device layer -------------------------------------------------------------

TPU_SOCKET_DIR = _str(
    "GRIT_TPU_SOCKET_DIR", "/tmp",
    "Directory of the per-pid agentlet toggle sockets "
    "(grit-tpu-<pid>.sock) shared by workload and agent.")
TPU_RESTORE_DIR = _str(
    "GRIT_TPU_RESTORE_DIR", "",
    "HBM snapshot dir to restore from; injected by the shim on "
    "restore-mode creates (grit.dev/checkpoint annotation path).")
TPU_COMPILE_CACHE = _str(
    "GRIT_TPU_COMPILE_CACHE", "",
    "Persistent XLA compilation-cache dir the snapshot carries; the pod "
    "webhook injects the default onto restore pods.")
RESTORE_PIPELINE = _bool(
    "GRIT_RESTORE_PIPELINE", True,
    "Pipelined (read/place overlapped) restore data path; =0 forces the "
    "serial fallback CI keeps green.")
TPU_RESTORE_WORKERS = _int(
    "GRIT_TPU_RESTORE_WORKERS", -1,
    "Read-ahead worker threads on the restore path; -1 (unset) sizes "
    "from the host's cores, 0 disables read-ahead.")
TPU_NATIVE = _bool(
    "GRIT_TPU_NATIVE", True,
    "Load the native gritio library (O_DIRECT + hw CRC32C); =0 forces "
    "the pure-python data plane.")
IO_NATIVE = _bool(
    "GRIT_IO_NATIVE", True,
    "Native file data plane (gritio-file: fused CRC+codec dump drain, "
    "batched container place); =0 forces the Python byte loops — the "
    "degrade is loud (io.degrade flight event + grit_io_degrade_total).")
IO_URING = _bool(
    "GRIT_IO_URING", True,
    "Allow io_uring for the native plane's batched stage->place reads; "
    "=0 (or a kernel without it) uses the concurrent-pread fallback.")
IO_PLACE_DEPTH = _int(
    "GRIT_IO_PLACE_DEPTH", 8,
    "Queue depth of the native plane's batched reads (io_uring ring "
    "entries / concurrent pread workers) — the disks under this are "
    "queue-depth machines (QD1 0.13 GB/s vs QD4 2.2 GB/s measured).")
SNAP_SPECULATE = _bool(
    "GRIT_SNAP_SPECULATE", True,
    "Quiesce-free concurrent dump: a quiesce request that carries a "
    "dump spec starts the snapshot speculatively against a cloned "
    "state generation while the loop is still stepping; the parked "
    "dump then re-ships only the arrays the in-flight step touched "
    "(validated delta). =0 restores the fully-parked dump path.")
SNAP_SPECULATE_WAIT_S = _float(
    "GRIT_SNAP_SPECULATE_WAIT_S", 120.0,
    "Bound on joining an in-flight speculative pass at dump time; a "
    "pass that outlives it degrades loudly to the parked full dump "
    "(bit-identical either way).")
TPU_DEV_ROOT = _str(
    "GRIT_TPU_DEV_ROOT", "/host-dev",
    "Host /dev mount the CDI generator scans for TPU device nodes.")
TPU_IMAGE_DIR = _declare(
    "GRIT_TPU_IMAGE_DIR", "", "str",
    "Where the CRIU TPU plugin (native/criu_tpu_plugin) writes/reads "
    "the HBM image during a criu dump/restore. Read by native code.",
    scope="native")
TPU_CHECKPOINT_BIN = _declare(
    "GRIT_TPU_CHECKPOINT_BIN", "", "str",
    "Path to the tpu-checkpoint toggle CLI the CRIU TPU plugin invokes. "
    "Read by native code.",
    scope="native")

# -- CRI / runtime adapters ---------------------------------------------------

CRIU_TIMEOUT_S = _float(
    "GRIT_CRIU_TIMEOUT_S", 600.0,
    "Hard ceiling on one criu invocation; a wedged criu (D-state task, "
    "fuse mount) must fail inside its phase deadline.")
SHIM_SOCKET_DIR = _str(
    "GRIT_SHIM_SOCKET_DIR", "/run/containerd/grit-tpu",
    "Directory of the runtime shim's per-sandbox TTRPC sockets.")
HOST_MOUNTINFO = _str(
    "GRIT_HOST_MOUNTINFO", "",
    "mountinfo file resolving container rootfs overlays; unset picks "
    "/proc/1/mountinfo when readable (hostPID agent pod), else "
    "/proc/self/mountinfo.")

# -- manager / control plane --------------------------------------------------

MASTER = _str(
    "GRIT_MASTER", "",
    "apiserver URL for the manager (outranks in-cluster/kubeconfig "
    "detection).")
TOKEN = _str(
    "GRIT_TOKEN", "",
    "Bearer token paired with GRIT_MASTER.")

# -- observability / fault injection / misc -----------------------------------

TPU_TRACE_FILE = _str(
    "GRIT_TPU_TRACE_FILE", "",
    "JSONL span sink enabling the tracing layer (unset: tracing off).")
FLIGHT = _bool(
    "GRIT_FLIGHT", False,
    "Per-migration flight recorder (grit_tpu.obs.flight): phase-boundary "
    "events appended crash-safe to .grit-flight.jsonl in the agent "
    "work/stage dir, analyzed by tools/gritscope. Default off; the "
    "obs/chaos lanes and bench enable it.")
FLIGHT_DIR = _str(
    "GRIT_FLIGHT_DIR", "",
    "Optional artifact tee for flight events: every event is ALSO "
    "appended to <dir>/flight-<host>-<pid>.jsonl so a CI lane can "
    "collect one artifact tree across many per-migration logs.")
FLIGHT_CLOCK = _str(
    "GRIT_FLIGHT_CLOCK", "",
    "Manager-stamped wall/monotonic clock pair (JSON) in the agent Job "
    "env; the agent echoes it as a clock.manager flight event so "
    "gritscope can place manager events on the agent timeline.")
PROF_HZ = _float(
    "GRIT_PROF_HZ", 25.0,
    "Sampling rate of the phase-scoped profiler (grit_tpu.obs.profile): "
    "while a flight-recorded phase bracket is open, all threads are "
    "sampled at this rate and each sample is classified python/native/"
    "syscall/lock/idle; collapsed stacks land next to the flight log as "
    ".grit-prof-<phase>.folded. 0 disables sampling entirely. The "
    "profiler only ever arms on flight events, so with GRIT_FLIGHT off "
    "this knob costs nothing.")
PROF_MAX_STACKS = _int(
    "GRIT_PROF_MAX_STACKS", 512,
    "Unique-stack cardinality cap per profiled phase: beyond it, new "
    "stacks fold into one [overflow] bucket instead of growing the "
    "sample table without bound (a pathological thread churning frames "
    "must not turn the profiler into the leak it is hunting).")
OBS_SAMPLE_S = _float(
    "GRIT_OBS_SAMPLE_S", 5.0,
    "Period of the observability sampler thread (grit_tpu.obs.sampler): "
    "refreshes edge-triggered gauges (codec queue depth, heartbeat age) "
    "and the live migration progress gauges/snapshot files between "
    "events, so a /metrics scrape never reads a stale edge.")
PROGRESS_STALL_S = _float(
    "GRIT_PROGRESS_STALL_S", 180.0,
    "Manager watchdog stall threshold on the grit.dev/progress Job "
    "annotation: a migration whose lease still beats but whose "
    "bytes/round/phase have not advanced for this long classifies "
    "retriable (ProgressStalled) — a frozen transfer is caught without "
    "waiting out the full phase deadline. 0 disables the check.")
WORKLOAD_METRICS_PORT = _int(
    "GRIT_WORKLOAD_METRICS_PORT", 0,
    "Opt-in workload-side /metrics server: when set, the workload "
    "process (agentlet install, restored-pod prefetch) serves its own "
    "registry — place/codec/post-copy-tail metrics are scrapeable "
    "DURING blackout, when only this process has them. 0 (default) "
    "serves nothing.")
TPU_GIT_SHA = _str(
    "GRIT_TPU_GIT_SHA", "",
    "Build-time git sha override for --version surfaces (container "
    "images have no .git).")
CHAOS_SEED = _declare(
    "GRIT_CHAOS_SEED", "", "str",
    "Seed for the chaos lane's randomized-but-reproducible fault menu "
    "(make test-chaos defaults it to the UTC date). Read by the test "
    "suite only.",
    scope="tests")
FAULT_POINTS = _str(
    "GRIT_FAULT_POINTS", "",
    "Fault-injection spec <point>:<mode>[:<arg>][:xN][,...] — see "
    "grit_tpu.faults; propagated from the grit.dev/fault-points CR "
    "annotation into both agent Jobs.")


# Access is deliberately attribute-only (config.KNOB.get() / .name):
# a by-env-name lookup helper would reintroduce the stringly-typed
# access path the registry exists to retire.

# The knob-reference table (docs/config-reference.md) is rendered by
# tools/gritlint/refs.py from an AST parse of THIS file — one renderer
# for the real tree and the lint fixtures alike. Regenerate with
# ``python -m tools.gritlint --write-refs``; the env-contract rule fails
# the build when the committed table drifts.
