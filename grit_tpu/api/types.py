"""Checkpoint/Restore resource types and phase enums.

Behavioral parity with reference ``pkg/apis/v1alpha1/checkpoint.go:13-76`` and
``pkg/apis/v1alpha1/restore.go:12-68``: same phase sets, same spec/status
fields (podName, volumeClaim, autoMigration; nodeName, podSpecHash, podUID,
phase, conditions, dataPath; checkpointName, ownerRef, selector; targetPod).
Implemented as plain dataclasses on top of :mod:`grit_tpu.kube.objects`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from grit_tpu.kube.objects import (
    Condition,
    LabelSelector,
    ObjectMeta,
    OwnerReference,
)


class CheckpointPhase(str, enum.Enum):
    """Checkpoint state machine: Created → Pending → Checkpointing →
    Checkpointed → Submitting → Submitted, or Failed.
    (reference checkpoint.go:13-21, state diagram at checkpoint.go:50)."""

    CREATED = "Created"
    PENDING = "Pending"
    CHECKPOINTING = "Checkpointing"
    # Standby mode (spec.standby): the agent armed — round-0 base
    # shipped, governed delta rounds keep it warm forever. Unbounded by
    # design (no phase deadline; the StandbyStale watchdog verdict
    # bounds a frozen governor instead).
    STANDBY = "Standby"
    # Standby fired (reclaim notice / cordon / grit.dev/fire): the agent
    # is running the final momentary-quiesce delta + blackout commit.
    FIRING = "Firing"
    CHECKPOINTED = "Checkpointed"
    SUBMITTING = "Submitting"  # auto-migration: Restore CR being created
    SUBMITTED = "Submitted"  # auto-migration: source pod deleted
    FAILED = "Failed"


#: Checkpoint phases that hold a VERIFIED, consumable snapshot — what
#: the Restore validating webhook accepts, what a RestoreSet may clone
#: (admission AND the controller's level-triggered re-verify), and what
#: auto-migration hands to the restore leg. ONE shared tuple so the
#: three gates can never drift apart.
VERIFIED_SNAPSHOT_PHASES = (CheckpointPhase.CHECKPOINTED,
                            CheckpointPhase.SUBMITTING,
                            CheckpointPhase.SUBMITTED)


#: Checkpoint phases a standby fire can still usefully land in: armed
#: (Standby), or any pre-armed phase — the checkpoint controller
#: forwards the annotation the moment the agent can consume it, and the
#: agent polls between rounds, so a mid-arm fire pays whatever base has
#: shipped so far (which beats a cold dump). ONE shared tuple for the
#: preemption watcher and the drain controller's cordon-fire/uncordon-
#: disarm paths, so fire and disarm eligibility can never drift apart.
STANDBY_PRE_FIRED_PHASES = (None, CheckpointPhase.CREATED,
                            CheckpointPhase.PENDING,
                            CheckpointPhase.CHECKPOINTING,
                            CheckpointPhase.STANDBY)


class RestorePhase(str, enum.Enum):
    """Restore state machine: Created → Pending → Restoring → Restored, or
    Failed (reference restore.go:12-18)."""

    CREATED = "Created"
    PENDING = "Pending"
    RESTORING = "Restoring"
    RESTORED = "Restored"
    FAILED = "Failed"


@dataclass
class VolumeClaimSource:
    """PVC reference used for cross-node checkpoint data sharing
    (reference checkpoint.go:30: PersistentVolumeClaimVolumeSource)."""

    claim_name: str
    read_only: bool = False


@dataclass
class CheckpointSpec:
    """reference checkpoint.go:23-37."""

    # Pod (same namespace) to checkpoint.
    pod_name: str = ""
    # Cloud storage for sharing checkpoint data across nodes; must be Bound
    # before the Checkpoint is admitted (validated by the checkpoint webhook).
    volume_claim: VolumeClaimSource | None = None
    # When true, the manager creates a Restore carrying the pod's controller
    # ownerRef and deletes the source pod, letting the owner (Deployment/Job)
    # recreate it as the restoration target (checkpoint.go:31-36).
    auto_migration: bool = False
    # Pre-copy live migration: the agent first ships a full HBM snapshot
    # while the workload keeps training, then dumps only the delta inside
    # the blackout window. TPU-native addition — the reference's opaque
    # CRIU process images cannot be diffed.
    pre_copy: bool = False
    # Preemption-armed standby (ROADMAP item 5): instead of one bounded
    # pre-copy loop ending in blackout, the agent stays resident after
    # the round-0 full dump and runs the delta-dump→flatten loop forever
    # on a dirty-rate-governed cadence, keeping a warm flattened base on
    # the destination. A fire signal (grit.dev/fire, spot reclaim taint,
    # drain cordon) then pays only the final delta + blackout. Implies
    # pre_copy semantics for the fired leg.
    standby: bool = False
    # Multi-host slices: all hosts agree on a step boundary before the
    # HBM dump. The cooperative toggle protocol ALWAYS cuts at a step
    # boundary (there is no preemptive mid-collective dump on TPU), so
    # false is recorded but cannot weaken the guarantee.
    consistent_cut: bool = True
    # Gang slice migration (ROADMAP item 1): host count of the slice.
    # 0/1 = the single-host flow, byte-identical to every PR before
    # this one. >1 turns this CR into a gang: pod_name names the
    # per-host pod PREFIX (host k's pod is "<pod_name>-<k>", the
    # JobSet convention), the manager runs one leased agent Job per
    # host (grit-agent-<name>-h<k>), folds per-host state into
    # status.hosts[], and finishes all-or-nothing — any host's
    # terminal verdict drives run_abort on EVERY source host, and the
    # slice is Checkpointed only when every host's leg completed.
    slice_hosts: int = 0
    # Data lifecycle (TPU-native addition; reference checkpoint data
    # accumulates on the PVC forever): after the checkpoint reaches its
    # terminal success phase and this many seconds elapse, the manager
    # runs a cleanup agent Job (deletes the PVC payload + host work dir)
    # and then deletes this CR — the Job.ttlSecondsAfterFinished idiom
    # applied to checkpoint data. None = keep forever.
    ttl_seconds_after_finished: int | None = None


@dataclass
class CheckpointStatus:
    """reference checkpoint.go:39-59."""

    node_name: str = ""
    pod_spec_hash: str = ""
    pod_uid: str = ""
    phase: CheckpointPhase | None = None
    conditions: list[Condition] = field(default_factory=list)
    # "<pv>://<namespace>/<checkpoint-name>" once data landed on the PVC
    # (reference checkpoint_controller.go:163).
    data_path: str = ""
    # Live migration telemetry (TPU-native addition, no reference
    # analogue): the agent's grit.dev/progress Job annotation folded in
    # by the controller on the lease-renewal cadence — bytesShipped,
    # totalBytes, round, rateBps, etaSeconds, phase, advancedAt. The
    # fleet drain scheduler's bandwidth budgeting reads this. Slice CRs
    # additionally carry progress.hosts (per-ordinal snapshots) and
    # progress.hostPairs (the N×N per-host-pair bandwidth lines).
    progress: dict = field(default_factory=dict)
    # Gang slice migration fan-in: one record per host ordinal —
    # {"ordinal", "pod", "podUid", "node", "job", "state", "reason"} —
    # refreshed every reconcile while the gang runs. Empty for
    # single-host CRs.
    hosts: list = field(default_factory=list)


@dataclass
class Checkpoint:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CheckpointSpec = field(default_factory=CheckpointSpec)
    status: CheckpointStatus = field(default_factory=CheckpointStatus)

    kind = "Checkpoint"


@dataclass
class RestoreSpec:
    """reference restore.go:20-37."""

    # Checkpoint (same namespace) whose data restores the pod; must already be
    # phase Checkpointed/Submitting/Submitted (restore webhook).
    checkpoint_name: str = ""
    # Either ownerRef (controller-created pods) or selector (standalone pods)
    # selects the restoration pod; matching additionally requires pod-spec
    # hash equality with the Checkpoint (pod_restore_default.go:70-91).
    owner_ref: OwnerReference | None = None
    selector: LabelSelector | None = None


@dataclass
class RestoreStatus:
    """reference restore.go:39-52."""

    node_name: str = ""
    target_pod: str = ""
    phase: RestorePhase | None = None
    conditions: list[Condition] = field(default_factory=list)
    # Live restore-leg telemetry: the restore agent Job's
    # grit.dev/progress annotation folded in on the lease cadence
    # (frames received, place waterline bytes, rate, ETA).
    progress: dict = field(default_factory=dict)


@dataclass
class Restore:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: RestoreSpec = field(default_factory=RestoreSpec)
    status: RestoreStatus = field(default_factory=RestoreStatus)

    kind = "Restore"


# -- fleet migration scheduler (MigrationPlan) ---------------------------------
#
# TPU-native addition with no reference analogue (its migrations are
# always one operator-created CR acting alone): a MigrationPlan names a
# SET of pods to move, the candidate destinations with their declared
# capacity, and the budgets the wave must respect; the manager's fleet
# plan controller expands it into a rolling wave of ordinary Checkpoint
# CRs — placement by the bin-packing destination chooser, admission by
# the fleet token-bucket budgets, priority classes ordering the queue —
# and folds every member's outcome back into status.pods[].


class MigrationPlanPhase(str, enum.Enum):
    """MigrationPlan state machine: Planning → Migrating → Succeeded /
    PartiallyFailed (the terminal verdict carries per-pod reasons in
    ``status.pods[]``; a failed member never fails the plan outright —
    it rides the abort machine back to source and is either retried,
    bounded, or reported)."""

    PLANNING = "Planning"
    MIGRATING = "Migrating"
    SUCCEEDED = "Succeeded"
    PARTIALLY_FAILED = "PartiallyFailed"


#: Priority classes a pod may declare via the grit.dev/migration-priority
#: annotation. Latency-critical pods migrate in the fast window (they
#: preempt QUEUED slots on arrival — never in-flight migrations: a
#: half-migrated pod is worse than a late one); batch pods queue behind
#: them. One closed vocabulary: the queue-depth metric labels by it.
PRIORITY_LATENCY_CRITICAL = "latency-critical"
PRIORITY_BATCH = "batch"
PRIORITY_CLASSES = (PRIORITY_LATENCY_CRITICAL, PRIORITY_BATCH)


@dataclass
class MigrationPlanMember:
    """One pod the plan must move. ``volume_claim`` overrides the plan's
    shared claim (the drain path fills it from each pod's
    grit.dev/drain-volume-claim annotation — different pods on one node
    legitimately ship to different PVCs)."""

    pod_name: str = ""
    volume_claim: VolumeClaimSource | None = None


@dataclass
class MigrationPlanDestination:
    """One candidate destination node with its plan-declared capacity.
    ``capacity_gb`` bounds the summed HBM demand of members placed on
    it (0 = unbounded); ``topology`` (e.g. "2x2") must match a member
    pod's grit.dev/tpu-topology annotation when both declare one."""

    node_name: str = ""
    capacity_gb: float = 0.0
    topology: str = ""


@dataclass
class MigrationPlanBudget:
    """Fleet budgets the wave must never exceed. Zero-valued bandwidth
    fields fall back to the GRIT_FLEET_* defaults (0 there too =
    unlimited); ``max_concurrent`` <= 0 falls back to
    GRIT_FLEET_MAX_CONCURRENT."""

    # Global ceiling on member migrations in flight at once.
    max_concurrent: int = 0
    # Per source->destination link bytes/s ceiling, enforced by the
    # fleet token bucket and actuated per member via byte shaping
    # (GRIT_MIRROR_MAX_INFLIGHT_MB on the agent Job).
    link_bandwidth_bps: float = 0.0
    # Fleet-wide bytes/s ceiling across every link.
    fleet_bandwidth_bps: float = 0.0


@dataclass
class MigrationPlanSpec:
    # Pods (same namespace) to migrate; each becomes one plan-owned
    # Checkpoint{autoMigration, preCopy} member CR.
    members: list[MigrationPlanMember] = field(default_factory=list)
    # Default PVC for members that do not override one; a member with
    # neither is refused at admission.
    volume_claim: VolumeClaimSource | None = None
    # Candidate destinations the bin-packing chooser places onto.
    destinations: list[MigrationPlanDestination] = field(
        default_factory=list)
    budget: MigrationPlanBudget = field(
        default_factory=MigrationPlanBudget)
    # Pre-copy live migration for every member (the drain window's case;
    # False = cold blackout dumps).
    pre_copy: bool = True
    # Plan-level retries per pod AFTER a member CR's own bounded agent
    # attempts exhausted and its abort resumed the source: the plan
    # re-creates the member CR (possibly onto a different destination)
    # this many times before recording the pod as failed in
    # status.pods[]. <0 falls back to GRIT_FLEET_MAX_RETRIES.
    max_retries_per_pod: int = -1
    # Data lifecycle forwarded onto every member Checkpoint (the drain
    # path sets its 24 h default so repeated drains of long-lived
    # same-named pods never accumulate PVC payloads). None = keep.
    ttl_seconds_after_finished: int | None = None


@dataclass
class MigrationPlanStatus:
    phase: MigrationPlanPhase | None = None
    conditions: list[Condition] = field(default_factory=list)
    # One record per member pod, refreshed every reconcile:
    # {"pod", "podUid", "sourceNode", "priority", "state" (Queued |
    # Migrating | Succeeded | Retrying | Failed), "checkpoint",
    # "destination", "attempts", "reason"}.
    pods: list = field(default_factory=list)
    # Live budget utilization snapshot: {"concurrent", "maxConcurrent",
    # "fleetRateBps", "fleetBudgetBps", "links": {"src->dst": {...}},
    # "wave"} — the numbers `gritscope watch --plan` renders.
    budget: dict = field(default_factory=dict)
    # Wall clock of the first member admission / the terminal verdict;
    # their difference is the fleet makespan the bench gates.
    started_at: float = 0.0
    finished_at: float = 0.0
    makespan_seconds: float = 0.0


@dataclass
class MigrationPlan:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: MigrationPlanSpec = field(default_factory=MigrationPlanSpec)
    status: MigrationPlanStatus = field(
        default_factory=MigrationPlanStatus)

    kind = "MigrationPlan"


# -- serving snapshot fan-out (RestoreSet) -------------------------------------
#
# TPU-native addition with no reference analogue (its restores are
# always 1→1 recoveries): a RestoreSet treats one VERIFIED snapshot —
# the PVC container tree + sidecars a Checkpoint committed — as a
# TEMPLATE and fans it out into spec.replicas plan-owned Restore CRs in
# parallel. Each clone is an ordinary post-copy restore (hot set
# synchronous, cold KV tail faulted in behind traffic), so restore
# becomes the serving tier's autoscaling primitive rather than a
# recovery path (ROADMAP item 4; PhoenixOS validates starting the
# destination before the last bytes commit).


class RestoreSetPhase(str, enum.Enum):
    """RestoreSet state machine: Pending (template verify) → Cloning
    (fan-out in flight, status.replicas[] fan-in) → Ready (readyReplicas
    == replicas) / Degraded (every clone settled, some terminally
    failed — siblings serve; the failed replicas carry reasons) /
    Failed (the template itself is unusable: snapshot deleted or
    rolled back underneath the set)."""

    PENDING = "Pending"
    CLONING = "Cloning"
    READY = "Ready"
    DEGRADED = "Degraded"
    FAILED = "Failed"


@dataclass
class RestoreSetTemplate:
    """How each clone's Restore selects its target pod — the same two
    vehicles RestoreSpec offers. With N replica pods racing admission,
    the pod webhook's atomic claim hands each pod a DIFFERENT clone
    Restore, so one selector serves the whole set."""

    owner_ref: OwnerReference | None = None
    selector: LabelSelector | None = None


@dataclass
class RestoreSetSpec:
    # Checkpoint (same namespace) whose committed snapshot is the clone
    # template; must be verified (phase Checkpointed/Submitting/
    # Submitted) at admission and is re-verified level-triggered.
    snapshot_ref: str = ""
    # Clone count. The controller creates one plan-owned Restore per
    # ordinal ("<name>-clone-<k>"); >= 1, bounded by
    # GRIT_SERVE_MAX_CLONES at admission.
    replicas: int = 1
    template: RestoreSetTemplate = field(default_factory=RestoreSetTemplate)


@dataclass
class RestoreSetStatus:
    phase: RestoreSetPhase | None = None
    conditions: list[Condition] = field(default_factory=list)
    # One record per clone ordinal, refreshed every reconcile:
    # {"ordinal", "restore", "targetPod", "node", "state" (Pending |
    # Restoring | Ready | Failed), "reason", "progress"}.
    replicas: list = field(default_factory=list)
    # Clones whose Restore reached Restored — the readiness gate the
    # fan-out closes on (and the autoscaler's signal).
    ready_replicas: int = 0
    # Folded live telemetry: {"readyReplicas", "replicas": {name:
    # progress dict}} — what `gritscope watch --restoreset` renders.
    progress: dict = field(default_factory=dict)
    # Wall clock of the first clone creation / the terminal verdict;
    # their difference is the time-to-Nth-replica the bench gates.
    started_at: float = 0.0
    finished_at: float = 0.0


@dataclass
class RestoreSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: RestoreSetSpec = field(default_factory=RestoreSetSpec)
    status: RestoreSetStatus = field(default_factory=RestoreSetStatus)

    kind = "RestoreSet"
