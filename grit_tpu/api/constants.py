"""Shared label/annotation keys and API group constants.

Parity: reference ``pkg/apis/v1alpha1/constants.go:6-18`` and
``pkg/apis/v1alpha1/register.go:12-17``. The ``grit.dev/*`` annotation
namespace is the load-bearing rendezvous mechanism between the control plane
and the node runtime (SURVEY §1): the pod mutating webhook writes
``grit.dev/checkpoint`` onto a restoration pod, and the runtime shim reads it
back out of the OCI spec to turn a cold create into a restore.
"""

from grit_tpu.api import config

# API group/version for the custom resources.
API_GROUP = "grit.tpu.dev"
API_VERSION = "v1alpha1"

# Label key/value identifying grit-agent Jobs (reference constants.go:8-9).
GRIT_AGENT_LABEL = "grit.dev/helper"
# Agent-Job action marker ("checkpoint" | "restore" | "cleanup") — the
# controllers discriminate job purpose by this label, never by sniffing
# container args.
GRIT_AGENT_ACTION_LABEL = "grit.dev/agent-action"
GRIT_AGENT_NAME = "grit-agent"

# Annotations stamped on a restoration pod by the pod mutating webhook
# (reference constants.go:12-13). CHECKPOINT_DATA_PATH_ANNOTATION carries the
# node-local host path of the downloaded checkpoint data; it is the *only*
# signal the node runtime sees (SURVEY §2.1 pod-webhook row).
CHECKPOINT_DATA_PATH_ANNOTATION = "grit.dev/checkpoint"
RESTORE_NAME_ANNOTATION = "grit.dev/restore-name"

# Annotations used on Restore resources (reference constants.go:16-17).
POD_SPEC_HASH_ANNOTATION = "grit.dev/pod-spec-hash"
POD_SELECTED_ANNOTATION = "grit.dev/pod-selected"

# Sandbox-level creation-mode annotation used by the crictl test data
# (reference contrib/containerd/testdata/sandbox.json).
CREATION_MODE_ANNOTATION = "grit.dev/creation-mode"

# TPU-native additions: the device snapshot layer records the accelerator
# topology a checkpoint was taken on so restore can verify chip compatibility
# (mirrors the reference's same-GPU-model/driver constraint,
# docs/proposals/...md:263-270, but for TPU slice topology).
TPU_TOPOLOGY_ANNOTATION = "grit.dev/tpu-topology"

# Workload env contract for the persistent XLA compilation cache the
# snapshot carries (grit_tpu/device/hook.py); the pod webhook injects the
# default onto restore pods so the carry works without operator action.
# The knob itself lives in the config registry; this re-export keeps the
# annotation/env contract surface in one import for webhook consumers.
COMPILE_CACHE_ENV = config.TPU_COMPILE_CACHE.name
COMPILE_CACHE_DEFAULT_DIR = "/var/cache/grit-tpu/xla"
TPU_RUNTIME_VERSION_ANNOTATION = "grit.dev/tpu-runtime-version"

# Drain-triggered live migration (TPU-native addition; no reference
# analogue — its migrations are always operator-initiated CRs): pods
# opting in with this label are automatically checkpointed with
# auto-migration + pre-copy when their node is cordoned. The annotation
# names the PVC the checkpoint ships to (required for opted-in pods).
MIGRATE_ON_DRAIN_LABEL = "grit.dev/migrate-on-drain"
DRAIN_VOLUME_CLAIM_ANNOTATION = "grit.dev/drain-volume-claim"

# Preemption-armed standby (TPU-native addition; ROADMAP item 5): a
# StandbyCheckpoint keeps a rolling pre-copy base continuously flattened
# on the destination so a reclaim notice pays only the final delta +
# blackout. FIRE_ANNOTATION is the arm/fire protocol's trigger: set on
# the Checkpoint CR (by the preemption watcher, the drain controller's
# cordon path, or an operator) its value is the fire reason; the
# checkpoint controller forwards it onto the armed agent Job, whose
# standby loop polls for it and runs the final momentary-quiesce delta.
FIRE_ANNOTATION = "grit.dev/fire"
# Explicit operator/test preemption signal on a Node: the preemption
# watcher treats it exactly like a cloud reclaim taint.
PREEMPT_NODE_ANNOTATION = "grit.dev/preempt"
# Cloud reclaim-notice taints the preemption watcher fires on (GKE
# stamps the first on spot/preemptible VMs seconds before termination).
RECLAIM_TAINT_KEYS = (
    "cloud.google.com/impending-node-termination",
    "k8s.gke.io/graceful-shutdown",
)
# Node labels marking spot/preemptible capacity: pods opting into
# migrate-on-drain on such nodes get an always-warm StandbyCheckpoint at
# schedule time instead of a cold Checkpoint at cordon time.
SPOT_NODE_LABELS = (
    "cloud.google.com/gke-spot",
    "cloud.google.com/gke-preemptible",
)

# Migration data path selection (TPU-native addition): "pvc" (default,
# double hop through the checkpoint PVC) or "wire" (direct source→
# destination stream with the PVC upload demoted to an async durability
# tee). Set on the Checkpoint CR; the manager propagates it into BOTH
# agent Jobs (checkpoint and restore) as GRIT_MIGRATION_PATH — the two
# agents rendezvous through the wire-endpoint file in the checkpoint's
# PVC work dir.
MIGRATION_PATH_ANNOTATION = "grit.dev/migration-path"

# Fault injection (grit_tpu/faults.py): a GRIT_FAULT_POINTS spec set on
# the Checkpoint CR, propagated by the manager into BOTH agent Jobs
# exactly like the migration path — so the chaos suite can arm a fault
# in a specific migration's node legs from the control plane.
FAULT_POINTS_ANNOTATION = "grit.dev/fault-points"

# Leased migration phases (agent/lease.py + the controller watchdogs):
# the agent renews HEARTBEAT_ANNOTATION (unix seconds) on its own Job;
# the manager fails the attempt over to retry/abort once it goes stale.
# ATTEMPT_ANNOTATION on the CR counts agent-Job attempts so retries stay
# bounded; RETRY_AT_ANNOTATION (unix seconds) is the earliest moment the
# next attempt's Job may be created (capped exponential backoff+jitter).
HEARTBEAT_ANNOTATION = "grit.dev/heartbeat"
ATTEMPT_ANNOTATION = "grit.dev/attempt"
RETRY_AT_ANNOTATION = "grit.dev/retry-at"

# Live migration progress (grit_tpu.obs.progress): the agent's heartbeat
# lease stamps this JSON snapshot (bytesShipped, totalBytes, round,
# rateBps, etaSeconds, advancedAt, ...) onto its own Job in the SAME
# patch as the lease renewal, and the manager controllers fold it into
# the CR's status.progress subresource — live per-migration telemetry
# with zero extra write amplification. The watchdog additionally reads
# advancedAt for progress-stall detection (GRIT_PROGRESS_STALL_S).
PROGRESS_ANNOTATION = "grit.dev/progress"

# Fleet migration scheduler (MigrationPlan; ROADMAP item 3). Pods
# declare their migration priority class (latency-critical | batch, see
# api.types.PRIORITY_CLASSES) with MIGRATION_PRIORITY_ANNOTATION —
# latency-critical members preempt QUEUED slots in the plan's admission
# order (never in-flight migrations). HBM_DEMAND_ANNOTATION declares the
# pod's state footprint in GB for the bin-packing destination chooser
# (fallback: google.com/tpu chip count x GRIT_FLEET_HBM_PER_CHIP_GB).
MIGRATION_PRIORITY_ANNOTATION = "grit.dev/migration-priority"
HBM_DEMAND_ANNOTATION = "grit.dev/hbm-gb"
# Stamped by the plan controller onto each member Checkpoint: the
# destination node the bin-packer chose (advisory placement record the
# per-link budget accounting keys by — the nodePairs progress line uses
# it as the dst half of its "src->dst" key), and the member's byte-
# shaping share of its link budget, which the checkpoint controller
# forwards into the agent Job env as GRIT_MIRROR_MAX_INFLIGHT_MB.
DESTINATION_NODE_ANNOTATION = "grit.dev/destination-node"
MAX_INFLIGHT_MB_ANNOTATION = "grit.dev/max-inflight-mb"

# Serving snapshot fan-out (RestoreSet; ROADMAP item 4). Each clone
# Restore the RestoreSet controller creates carries its owning set's
# name and its ordinal, so the fan-in (status.replicas[]), gritscope's
# fan-out view, and operators can key a clone leg back to its set
# without parsing generated names.
RESTORESET_ANNOTATION = "grit.dev/restoreset"
CLONE_ORDINAL_ANNOTATION = "grit.dev/clone-ordinal"

# W3C traceparent carried across the manager -> agent-Job process
# boundary so a migration's spans share one trace (grit_tpu/obs/trace.py
# re-exports this for its consumers).
TRACEPARENT_ANNOTATION = "grit.dev/traceparent"

# Flight-recorder clock anchor: the manager stamps its own wall/monotonic
# pair (JSON) on the Checkpoint/Restore CR when flight recording is on;
# the AgentManager forwards it into the agent Job env (GRIT_FLIGHT_CLOCK)
# and the agent echoes it as a clock.manager flight event — the
# Job-annotation half of gritscope's cross-process clock alignment (the
# wire commit handshake is the other half).
FLIGHT_CLOCK_ANNOTATION = "grit.dev/flight-clock"
